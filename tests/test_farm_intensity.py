"""Reference-intensity convergence farms.

The reference stresses merge-tree with up to 32 clients x 512 ops/round
(client.conflictFarm.spec.ts:50-57) and reconnect churn
(client.reconnectFarm.spec.ts). These farms run that client count and
per-round op volume — including a DEVICE-host replica ingesting the same
sequenced stream — asserting every replica and the device state match
after each drain."""

import os
import random

import pytest

pytestmark = [pytest.mark.soak, pytest.mark.slow]

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost
from tests.test_mergetree import get_string, make_string_doc, random_edit

# Rounds scale up via env for the full reference profile (32 rounds);
# the default keeps the always-on suite within budget at the reference's
# CLIENT COUNT and OPS/ROUND.
ROUNDS = int(os.environ.get("FARM_ROUNDS", "6"))


def _conflict_farm(n_clients: int, rounds: int,
                   require_device_ops: bool,
                   min_ops: int = 256, max_ops: int = 512) -> None:
    """Conflict farm body: every replica AND the device-host text must
    match after every round's drain. With require_device_ops the farm
    must stay ENTIRELY on the device path (overlap planes grow past 32
    writers instead of overflow-routing — VERDICT r3 item 1)."""
    rng = random.Random(7)
    host = KernelMergeHost(flush_threshold=512)
    server = LocalCollabServer(merge_host=host)
    c1 = make_string_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(n_clients - 1)]
    strings = [get_string(c) for c in containers]

    for round_no in range(rounds):
        paused = [c for c in containers if rng.random() < 0.3]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(min_ops, max_ops + 1)):
            random_edit(rng, strings[rng.randrange(len(strings))])
        for c in paused:
            c.inbound.resume()
        texts = [s.get_text() for s in strings]
        assert all(t == texts[0] for t in texts), round_no
        assert host.text("doc", "default", "text") == texts[0], round_no
    if require_device_ops:
        assert host.stats["device_ops"] > 0
        assert host.stats["overflow_routed"] == 0
        assert host.stats["scalar_ops"] == 0
    for c in containers:
        assert not c.nacks


def test_conflict_farm_reference_client_scale():
    """The reference's conflictFarm client scale — 32 clients x 256-512
    ops/round (client.conflictFarm.spec.ts:50-57) — fully device-served:
    zero ops on the scalar fallback."""
    _conflict_farm(32, ROUNDS, require_device_ops=True)


def test_conflict_farm_128_clients_device_served():
    """BASELINE config 2's client count (128 writers, one doc) stays on
    the device path: the overlap planes grow to 4 words and no channel
    overflow-routes."""
    _conflict_farm(128, max(2, ROUNDS // 3), require_device_ops=True,
                   min_ops=128, max_ops=256)


def test_reconnect_farm_reference_scale():
    """16 clients x 128-256 ops/round with random disconnect/reconnect
    (pending-op regeneration) + the device replica staying exact."""
    rng = random.Random(21)
    host = KernelMergeHost(flush_threshold=256)
    server = LocalCollabServer(merge_host=host)
    c1 = make_string_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(15)]

    for round_no in range(ROUNDS):
        dropped = [c for c in containers[1:] if rng.random() < 0.3]
        for c in dropped:
            c.disconnect()
        for _ in range(rng.randrange(128, 257)):
            c = containers[rng.randrange(len(containers))]
            random_edit(rng, get_string(c))
        for c in dropped:
            c.reconnect()
        texts = [get_string(c).get_text() for c in containers]
        assert all(t == texts[0] for t in texts), round_no
        assert host.text("doc", "default", "text") == texts[0], round_no
    summaries = [c.summarize() for c in containers]
    assert all(s == summaries[0] for s in summaries)


def test_merge_host_farm_many_docs():
    """Cross-document farm at service scale: 8 docs x 8 clients, all
    device-served, every doc's replicas + device state matching."""
    from tests.test_merge_host import get_parts, make_doc

    rng = random.Random(3)
    host = KernelMergeHost(flush_threshold=128)
    server = LocalCollabServer(merge_host=host)
    docs = []
    for d in range(8):
        c1 = make_doc(server, f"doc{d}")
        docs.append([c1] + [
            Container.load(LocalDocumentService(server, f"doc{d}"))
            for _ in range(7)])

    for _round in range(max(3, ROUNDS // 2)):
        for containers in docs:
            for _ in range(rng.randrange(32, 65)):
                c = containers[rng.randrange(len(containers))]
                text, root = get_parts(c)
                if rng.random() < 0.6:
                    random_edit(rng, text)
                else:
                    root.set(f"k{rng.randrange(8)}", rng.randrange(100))
    for d, containers in enumerate(docs):
        texts = [get_parts(c)[0].get_text() for c in containers]
        maps = [dict(get_parts(c)[1].data.items()) for c in containers]
        assert all(t == texts[0] for t in texts), d
        assert all(m == maps[0] for m in maps), d
        assert host.text(f"doc{d}", "default", "text") == texts[0], d
        assert host.map_entries(f"doc{d}", "default", "root") == maps[0], d
    assert host.stats["device_ops"] > 0


def test_matrix_reconnect_farm():
    """Matrix farm with reconnect churn at intensity: permutation-vector
    pending ops (incl. split-run insertGroup regeneration) + LWW cells
    must converge across 8 replicas and the device host."""
    from fluidframework_tpu.dds.matrix import SharedMatrix
    from tests.test_matrix import get_matrix, grid_of
    from tests.test_matrix_kernel import random_matrix_edit

    rng = random.Random(9)
    host = KernelMergeHost(flush_threshold=64)
    server = LocalCollabServer(merge_host=host)
    c1 = Container.create_detached(LocalDocumentService(server, "doc"))
    c1.runtime.create_datastore("default").create_channel(
        "grid", SharedMatrix.channel_type)
    c1.attach()
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(7)]
    get_matrix(c1).insert_rows(0, 2)
    get_matrix(c1).insert_cols(0, 2)

    for round_no in range(ROUNDS):
        dropped = [c for c in containers[1:] if rng.random() < 0.3]
        for c in dropped:
            c.disconnect()
        for _ in range(rng.randrange(48, 97)):
            c = containers[rng.randrange(len(containers))]
            random_matrix_edit(rng, get_matrix(c))
        for c in dropped:
            c.reconnect()
        grids = [grid_of(get_matrix(c)) for c in containers]
        assert all(g == grids[0] for g in grids), round_no
        assert host.matrix_grid("doc", "default", "grid") == grids[0], \
            round_no


@pytest.mark.skipif(os.environ.get("FARM_FULL") != "1",
                    reason="full 32-round reference profile: set FARM_FULL=1")
def test_conflict_farm_full_reference_profile():
    """The reference's FULL profile (32 clients x up to 512 ops/round x
    32 rounds), entirely device-served — minutes of wall time; run
    explicitly."""
    _conflict_farm(32, 32, require_device_ops=True)
