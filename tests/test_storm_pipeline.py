"""Pipelined serving tick (round 14): tick N's group fsync overlaps
tick N+1's scatter+dispatch, staged into double-buffered host
generations, with acks still withheld on the durable watermark.

Oracles: (1) a pipelined controller must converge byte-identically with
an unpipelined (pipeline_depth=0, serial dispatch→readback→fsync→ack)
twin fed the same frames — pipelining is a scheduling change, never a
semantic one; (2) a frame scattered into staging generation B while
generation A's tick is in flight must never alias A's arrays; (3) the
stage ledger must report wall-clock tick time with an explicit
overlap_ms instead of double-counting the concurrent commit-wait and
dispatch spans; (4) the client flow-control window frees on acks AND on
busy-nacks, but only acks count as acked.
"""

import queue
import threading
import time

import numpy as np
import pytest

from fluidframework_tpu.dds.map_data import MapData
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController


def build(tmp_path, name, pipeline_depth, num_docs=4, durability="group"):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    storm = StormController(
        service, seq_host, merge_host, flush_threshold_docs=num_docs,
        pipeline_depth=pipeline_depth,
        spill_dir=str(tmp_path / name) if durability else None,
        durability=durability)
    return service, storm, seq_host, merge_host


def join_docs(service, docs):
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in docs}
    service.pump()
    return clients


def make_words(seed, tick, doc_i, k, num_slots=16):
    rng = np.random.default_rng([seed, tick, doc_i])
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, num_slots, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def replay_oracle(service, doc_id):
    data = MapData()
    for m in service.get_deltas(doc_id, 0):
        if m.type != MessageType.OPERATION or not isinstance(m.contents,
                                                             dict):
            continue
        inner = m.contents.get("contents", {}).get("contents")
        if inner:
            data.process(inner, False, None)
    return dict(data.items())


def run_workload(service, storm, docs, clients, ticks=4, k=8,
                 ragged_tick=None):
    """``ticks`` frames per doc through the un-forced threshold flush
    (each frame IS one tick at threshold == len(docs)); a ragged tick
    (different K) exercises the staging-generation geometry change."""
    acks = []
    ack_counts = []
    for t in range(ticks):
        kk = k * 2 if t == ragged_tick else k
        entries = [[d, clients[d], 1 + t * k * 2, 1, kk] for d in docs]
        payload = b"".join(make_words(7, t, i, kk).tobytes()
                           for i in range(len(docs)))
        storm.submit_frame(acks.append, {"rid": t, "docs": entries},
                           memoryview(payload))
        ack_counts.append(len(acks))
    storm.flush()
    return acks, ack_counts


def digest(service, storm, seq_host, merge_host, docs):
    import dataclasses
    out = {}
    for d in docs:
        cp = dataclasses.asdict(seq_host.checkpoint(d))
        cp.pop("log_offset", None)
        for client in cp["clients"]:
            client["last_update"] = 0  # arrival clock, not replica state
        out[d] = {
            "map": merge_host.map_entries(d, storm.datastore,
                                          storm.channel),
            "history": [
                [m.sequence_number, m.client_sequence_number,
                 m.reference_sequence_number,
                 m.minimum_sequence_number, int(m.type)]
                for m in service.get_deltas(d, 0)],
            "sequencer": cp,
        }
    return out


class TestPipelinedMatchesUnpipelinedTwin:
    def test_two_tick_twin_diff_with_group_wal(self, tmp_path):
        """The generation-isolation pin: a pipelined run (frames
        scattered into generation B while generation A's tick is in
        flight, fsync overlapped with dispatch) must produce every
        plane byte-identical to the serial twin — including across a
        mid-run K change that reallocates a staging generation."""
        docs = [f"d{i}" for i in range(4)]
        planes = {}
        for name, depth in (("pipe", 1), ("serial", 0)):
            service, storm, seq_host, merge_host = build(
                tmp_path, name, pipeline_depth=depth)
            clients = join_docs(service, docs)
            acks, _counts = run_workload(service, storm, docs, clients,
                                         ticks=4, ragged_tick=2)
            assert len(acks) == 4 and not any(
                a.get("error") for a in acks)
            # acked ⇒ durable: every ack carries the watermark PAST its
            # tick, pipelined or not.
            for a in acks:
                assert a["dw"] > a["rid"]
            planes[name] = digest(service, storm, seq_host, merge_host,
                                  docs)
            for d in docs:
                assert merge_host.map_entries(
                    d, storm.datastore, storm.channel) \
                    == replay_oracle(service, d), (name, d)
            storm._group_wal.close()
        assert planes["pipe"] == planes["serial"]

    def test_pipelined_acks_lag_serial_acks_do_not(self, tmp_path):
        """Depth 1: a tick's ack is withheld while it (or its group
        commit) is still in flight — at most the earlier ticks have
        acked after each submit. Depth 0 (the fallback config): every
        submit returns with its own ack already delivered (dispatch →
        readback → fsync barrier → ack, inline)."""
        docs = ["a", "b"]
        service, storm, *_ = build(tmp_path, "pipe", pipeline_depth=1,
                                   num_docs=2)
        clients = join_docs(service, docs)
        _acks, counts = run_workload(service, storm, docs, clients,
                                     ticks=4)
        assert all(c <= t + 1 for t, c in enumerate(counts))
        assert counts[0] == 0  # first tick still in flight → no ack yet
        storm._group_wal.close()

        service, storm, *_ = build(tmp_path, "serial", pipeline_depth=0,
                                   num_docs=2)
        assert storm.pipeline_depth == 0
        clients = join_docs(service, docs)
        _acks, counts = run_workload(service, storm, docs, clients,
                                     ticks=4)
        assert counts == [1, 2, 3, 4]  # inline barrier: ack per round
        storm._group_wal.close()


class TestStagingGenerations:
    def test_consecutive_rounds_never_share_arrays(self, tmp_path):
        """Two ticks in flight windows never alias: consecutive rounds
        scatter into DISTINCT generation arrays (depth+1 ring), and a
        geometry change reallocates only the generation it lands on."""
        docs = ["a", "b"]
        service, storm, *_ = build(tmp_path, "gens", pipeline_depth=1,
                                   num_docs=2, durability=None)
        clients = join_docs(service, docs)
        seen = []
        real = storm._staging_gen

        def spy(b_seq, b_map, k):
            gen = real(b_seq, b_map, k)
            seen.append((id(gen["words"]), id(gen["slot"]), gen["shape"]))
            return gen

        storm._staging_gen = spy
        run_workload(service, storm, docs, clients, ticks=4,
                     ragged_tick=2)
        assert len(seen) == 4
        # Round t and t+1 never share a single staging array.
        for a, b in zip(seen, seen[1:]):
            assert a[0] != b[0] and a[1] != b[1]
        # The ragged tick (2x K) landed in a generation with the wider
        # shape; the steady rounds kept theirs.
        assert seen[2][2][2] == 2 * seen[0][2][2]
        assert len(storm._staging) == storm.pipeline_depth + 1
        for d in docs:
            assert replay_oracle(service, d) \
                == service.storm.merge_host.map_entries(
                    d, storm.datastore, storm.channel)

    def test_depth_zero_single_generation(self, tmp_path):
        service, storm, *_ = build(tmp_path, "one", pipeline_depth=0,
                                   num_docs=2, durability=None)
        assert len(storm._staging) == 1  # nothing ever in flight


class TestOverlapAttribution:
    def test_known_distribution_overlap_regression(self):
        """Known distribution: 4 ticks, wall 100 ms each; dispatch 60 ms
        inside the record, commit-wait 80 ms backfilled at drain (the
        pipelined shape — it ran under the NEXT tick's dispatch). The
        attribution must report wall 400 ms and overlap = attributed −
        wall = 160 ms — never a 560 ms "tick time" sum — and per-stage
        of_wall fractions that legitimately sum past 1.0."""
        from fluidframework_tpu.utils import StageLedger
        led = StageLedger()
        for t in range(4):
            rec = led.record(t, 0, 2, 64,
                             {"device_dispatch": 60_000_000},
                             wall_ns=100_000_000, depth=1)
            led.amend(rec, "wal_commit_wait", 80_000_000)
        att = led.attribution()
        win = att["_window"]
        assert win["wall_ms"] == 400.0
        assert win["attributed_ms"] == 560.0
        assert win["overlap_ms"] == 160.0
        assert win["pipeline_depth"] == 1
        assert att["device_dispatch"]["of_wall"] == 0.6
        assert att["wal_commit_wait"]["of_wall"] == 0.8
        # Shares (of attributed) still sum to 1 — the legacy surface.
        shares = [v["share"] for s, v in att.items() if s != "_window"]
        assert abs(sum(shares) - 1.0) < 0.01

    def test_no_wall_records_keep_legacy_shape(self):
        """Pre-pipelining records (wall 0): no of_wall keys, overlap 0 —
        the r10 consumers see exactly the shape they always did."""
        from fluidframework_tpu.utils import StageLedger
        led = StageLedger()
        led.record(0, 0, 1, 8, {"scatter": 1_000_000,
                                "device_dispatch": 3_000_000})
        att = led.attribution()
        assert "of_wall" not in att["scatter"]
        assert att["_window"]["overlap_ms"] == 0.0
        assert att["_window"]["wall_ms"] == 0.0
        assert att["_window"]["pipeline_depth"] == 0

    def test_serial_ticks_report_no_phantom_overlap(self, tmp_path):
        """A depth-0 controller's durability barrier is serving-thread
        time: it lands INSIDE the record (wall covers it), so the
        attribution of a genuinely sequential run shows ~zero overlap
        while the commit-wait stage itself is nonzero."""
        docs = ["a", "b"]
        service, storm, *_ = build(tmp_path, "ser", pipeline_depth=0,
                                   num_docs=2)
        clients = join_docs(service, docs)
        run_workload(service, storm, docs, clients, ticks=3)
        att = storm.ledger.attribution()
        win = att["_window"]
        assert win["wall_ms"] > 0
        assert att["wal_commit_wait"]["total_ms"] > 0
        # The serial run's overlap is measurement residue, never a
        # stage-sized artifact: bounded well below the commit-wait +
        # dispatch total that a double-counting ledger would report.
        assert win["overlap_ms"] < 0.5 * (
            att["wal_commit_wait"]["total_ms"]
            + att["device_dispatch"]["total_ms"])
        storm._group_wal.close()


class _FakeFlowService:
    """Duck-typed NetworkDocumentService surface StormStream touches."""

    def __init__(self):
        self._handlers = {}
        self._stamp_storm_rx = False
        self.sent = []

    def send_storm(self, header, payload):
        self.sent.append((header, payload))


class TestStormStreamWindow:
    def test_window_blocks_until_ack_frees_slot(self):
        from fluidframework_tpu.drivers.network_driver import StormStream
        svc = _FakeFlowService()
        stream = StormStream(svc, sample_every=0, window=1)
        stream.submit([["d", "c", 1, 1, 4]], b"\x00" * 16, rid=0)
        assert stream.inflight == 1
        submitted = threading.Event()

        def second():
            stream.submit([["d", "c", 5, 1, 4]], b"\x00" * 16, rid=1)
            submitted.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not submitted.is_set()  # window full: submit blocks
        svc._handlers["storm_ack"]({"rid": 0, "storm": True,
                                    "acks": [[4, 1, 4, 1]]})
        assert submitted.wait(5.0)
        t.join(5.0)
        assert stream.acked == 1 and stream.nacked == 0
        assert len(svc.sent) == 2

    def test_window_full_times_out(self):
        from fluidframework_tpu.drivers.network_driver import StormStream
        svc = _FakeFlowService()
        stream = StormStream(svc, sample_every=0, window=1)
        stream.submit([["d", "c", 1, 1, 4]], b"", rid=0)
        with pytest.raises(TimeoutError, match="window 1 still full"):
            stream.submit([["d", "c", 5, 1, 4]], b"", rid=1,
                          timeout=0.05)

    def test_busy_nack_frees_slot_but_never_counts_acked(self):
        """The round-14 satellite fix: a shed frame's busy-nack frees
        the window slot (the budget really is free) but counts on
        .nacked — not .acked, it was never sequenced — and arms the
        retry_after_s backoff the next submit honors."""
        from fluidframework_tpu.drivers.network_driver import StormStream
        svc = _FakeFlowService()
        nacks = []
        stream = StormStream(svc, sample_every=0, window=1,
                             on_nack=nacks.append)
        stream.submit([["d", "c", 1, 1, 4]], b"", rid=0)
        t0 = time.monotonic()
        svc._handlers["storm_ack"]({"rid": 0, "storm": True,
                                    "error": "busy", "retryable": True,
                                    "retry_after_s": 0.15})
        assert stream.inflight == 0
        assert stream.acked == 0 and stream.nacked == 1
        assert nacks and nacks[0]["error"] == "busy"
        # The next submit sleeps out the hint before sending.
        stream.submit([["d", "c", 1, 1, 4]], b"", rid=1)
        assert time.monotonic() - t0 >= 0.10
        assert len(svc.sent) == 2

    def test_unwindowed_stream_keeps_legacy_shape(self):
        from fluidframework_tpu.drivers.network_driver import StormStream
        svc = _FakeFlowService()
        stream = StormStream(svc, sample_every=0)
        for rid in range(8):  # never blocks, inflight never enforced
            stream.submit([["d", "c", 1, 1, 4]], b"", rid=rid)
        assert len(svc.sent) == 8
        with pytest.raises(ValueError, match="window must be >= 1"):
            StormStream(svc, window=0)


def test_dispatch_routes_json_storm_nack_to_ack_handler():
    """A JSON-path storm nack (shed/quarantine refusal) carries the
    SENDER's frame rid — before round 14 the rid routing dropped it on
    the floor (no RPC waiter ever registered it), silently freeing
    client budget. It must reach the storm_ack handler like any binary
    ack, rx-stamped when a trace consumer is attached."""
    from types import SimpleNamespace

    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentService,
    )

    stub = SimpleNamespace(_events=queue.Queue(), _pending={},
                           _stamp_storm_rx=True)
    nack = {"rid": 7, "storm": True, "error": "busy",
            "retry_after_s": 0.05}
    NetworkDocumentService._dispatch(stub, nack)
    routed = stub._events.get_nowait()
    assert routed["event"] == "storm_ack"
    assert routed["error"] == "busy" and routed["_rx_ns"] > 0
    assert not stub._pending  # never consumed as an RPC response
    # Plain RPC responses still route to their waiters untouched.
    waiter = queue.Queue()
    stub._pending[3] = waiter
    NetworkDocumentService._dispatch(stub, {"rid": 3, "ok": True})
    assert waiter.get_nowait() == {"rid": 3, "ok": True}
