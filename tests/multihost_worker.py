"""Worker process for the REAL 2-process jax.distributed serving test.

Run as: python multihost_worker.py <process_id> <num_processes> <port>

Each process owns 4 virtual CPU devices; jax.distributed assembles the
8-device global mesh over DCN (the per-process partition-consumer model,
lambdas-driver/src/kafka-service/partitionManager.ts:24). The process
feeds ONLY its local_docs rows, runs the fused SPMD storm tick, harvests
only its shard, and cross-checks the global psum metrics — which can
only be right if the collective really ran across both processes.
"""

import os
import sys


def main() -> None:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    port = sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_processes, process_id=process_id)
    assert jax.process_count() == num_processes
    assert len(jax.devices()) == 4 * num_processes, len(jax.devices())
    assert len(jax.local_devices()) == 4

    import numpy as np

    from fluidframework_tpu.parallel import multihost
    from fluidframework_tpu.parallel.serving import ShardedServing

    mesh = multihost.global_mesh()
    num_docs, k = 16, 8
    serving = ShardedServing(mesh, num_docs=num_docs, k=k,
                             num_hosts=num_processes)
    lo, hi = serving.local_lo, serving.local_hi
    span = num_docs // num_processes
    assert (lo, hi) == (process_id * span, (process_id + 1) * span), (lo, hi)
    port_mine = serving.hosts[process_id]
    assert (port_mine.start, port_mine.stop) == (lo, hi)

    serving.join_all()

    # Distinct per-row op batches: k set-ops on slots 0..k-1, value
    # derived from the row so convergence is checkable per shard.
    def words_for(row: int) -> np.ndarray:
        slots = np.arange(k, dtype=np.uint32)
        values = (1000 + row * 10 + slots).astype(np.uint32)
        return (0 | (slots << 2) | (values << 12)).astype(np.uint32)

    for row in range(lo, hi):
        serving.submit(row, words_for(row), first_cseq=1)
    harvest = serving.tick()

    mine = harvest[process_id]
    assert set(mine.keys()) == set(range(lo, hi)), mine
    for row, (n_seq, first, last) in mine.items():
        assert n_seq == k, (row, n_seq)
        assert first == 2 and last == k + 1, (row, first, last)
    for other in range(num_processes):
        if other != process_id:
            assert harvest[other] == {}, harvest[other]

    local_rows = serving.local_map_rows()
    assert set(local_rows.keys()) == set(range(lo, hi))
    for row, plane in local_rows.items():
        want = 1000 + row * 10 + np.arange(k)
        assert np.array_equal(plane[:k], want), (row, plane[:k], want)

    # Global totals ride a psum across BOTH processes: per doc the join
    # (1) + k ops sequenced, over every doc of every host.
    metrics = serving.global_metrics()
    assert metrics["seq"] == num_docs * (k + 1), metrics
    assert metrics["present"] == num_docs * k, metrics

    print(f"OK process {process_id}", flush=True)


if __name__ == "__main__":
    main()
