"""Tiered hot/cold doc residency (server/residency.py): hydrate on
cold connect/first-op, idle + capacity eviction through the durable
snapshot tier, byte-identical re-hydration, admission-gated hydration
storms, refusal invariants (quarantine pins, degraded WAL), bounded
per-doc bookkeeping under churn, and the bounded cohort LRU."""

import dataclasses

import numpy as np
import pytest

from fluidframework_tpu.server.durable_store import (
    DurableMessageBus,
    FileStateStore,
    GitSnapshotStore,
)
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import ChannelKey, KernelMergeHost
from fluidframework_tpu.server.residency import (
    COLD_KEY_PREFIX,
    EvictionRefused,
    ResidencyManager,
)
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController
from fluidframework_tpu.tools import chaos
from fluidframework_tpu.utils import CountedLRU
from fluidframework_tpu.utils.metrics import MetricsRegistry

K = 8


def build_stack(tmp_path, num_docs=4, residency=True, clock=None,
                storm_kw=None, **res_kw):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    # Durable bus + store (the deli/scriptorium pair): the recovery test
    # rebuilds a stack over the same directories, and client joins must
    # survive the restart exactly as in the chaos harness stack.
    service = RouterliciousService(
        bus=DurableMessageBus(str(tmp_path / "bus")),
        store=FileStateStore(str(tmp_path / "state")),
        merge_host=merge_host, batched_deli_host=seq_host,
        auto_pump=False, idle_check_interval=10**9)
    storm = StormController(
        service, seq_host, merge_host, flush_threshold_docs=10**9,
        spill_dir=str(tmp_path / "spill"), durability="group",
        snapshots=GitSnapshotStore(tmp_path / "git"),
        **(storm_kw or {}))
    res = None
    if residency:
        kw = dict(idle_evict_s=1e9, hydration_rate_per_s=1e9)
        kw.update(res_kw)
        if clock is not None:
            kw["clock"] = clock
        res = ResidencyManager(storm, **kw)
    return service, storm, seq_host, merge_host, res


def tick_words(seed, k=K):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def set_words(r, k=K):
    """Deterministic SET-only words: slot i <- value r*K+i+1 (no clears,
    so the converged planes are guaranteed non-trivial)."""
    slots = np.arange(k, dtype=np.uint32)
    vals = np.arange(1 + r * k, 1 + (r + 1) * k, dtype=np.uint32)
    return (slots << np.uint32(2)) | (vals << np.uint32(12))


def drive(storm, doc, client, r, k=K, push=None, rid=None, words=None):
    """One per-doc frame + settle (the per-doc shape residency gates)."""
    import zlib
    payload = (words if words is not None
               else tick_words((zlib.crc32(doc.encode()) & 0xFFFF, r),
                               k)).tobytes()
    storm.submit_frame(push,
                       {"rid": r if rid is None else rid,
                        "docs": [[doc, client, 1 + r * k, 1, k]]},
                       memoryview(payload))
    storm.flush()


def connect_docs(service, docs):
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in docs}
    service.pump()
    return clients


class TestLifecycle:
    def test_evict_then_cold_first_op_hydrates(self, tmp_path):
        service, storm, seq_host, merge_host, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a", "b"])
        for r in range(2):
            for d in ("a", "b"):
                drive(storm, d, clients[d], r, words=set_words(r))
        ckey = ChannelKey("a", storm.datastore, storm.channel)

        def planes_of(doc):
            row = merge_host._map_rows[ckey].row
            xs = merge_host._xstate
            return {f: np.asarray(getattr(xs, f)[row])
                    for f in ("present", "value", "vseq")}

        before_planes = planes_of("a")
        before_cp = dataclasses.asdict(seq_host.checkpoint("a"))
        assert np.asarray(before_planes["vseq"]).max() > 0  # served state

        handle = res.evict("a")
        assert handle
        assert not res.is_resident("a")
        assert "a" not in seq_host._rows  # device row released
        assert ckey not in merge_host._map_rows
        assert "a" not in storm._doc_ticks  # bookkeeping trimmed
        assert "a" not in storm.doc_tick_counts
        assert res.cold_handle("a") == handle
        assert storm.snapshots.head(COLD_KEY_PREFIX + "a") == handle

        # Hydration restores the planes byte-identically (no ops between
        # the evict and the hydrate).
        res.ensure_resident("a", gate=False)
        assert res.is_resident("a")
        assert res.stats["cold_hydrations"] == 1
        after_planes = planes_of("a")
        for f, want in before_planes.items():
            assert np.array_equal(after_planes[f], want), f
        assert dataclasses.asdict(seq_host.checkpoint("a")) == before_cp

        # And the hydrated doc keeps serving: first op acks cleanly.
        acks = []
        drive(storm, "a", clients["a"], 2, push=acks.append)
        assert acks and not acks[0].get("error")

    def test_cold_connect_hydrates(self, tmp_path):
        service, storm, seq_host, _mh, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a"])
        drive(storm, "a", clients["a"], 0)
        res.evict("a")
        assert not res.is_resident("a")
        # A NEW connect against the cold doc hydrates (PAPER §2.6: the
        # document loads on connect).
        service.connect("a", lambda m: None)
        assert res.is_resident("a")
        assert res.stats["cold_hydrations"] == 1

    def test_cold_doc_catchup_read_without_hydration(self, tmp_path):
        """A gap fetch against a COLD doc must return the full history
        (served from the cold snapshot's tick index) WITHOUT hydrating —
        readers must not churn the pool."""
        service, storm, *_, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a"])
        for r in range(3):
            drive(storm, "a", clients["a"], r)
        want = [(m.sequence_number, m.client_sequence_number)
                for m in service.get_deltas("a", 0)]
        assert len(want) >= 3 * K
        res.evict("a")
        got = [(m.sequence_number, m.client_sequence_number)
               for m in service.get_deltas("a", 0)]
        assert got == want
        assert not res.is_resident("a")  # the read did NOT hydrate

    def test_disconnect_cold_doc_does_not_leak_untracked_row(
            self, tmp_path):
        """A CLIENT_LEAVE against a cold doc sequences through the deli
        row — it must hydrate into a TRACKED slot first, or the leave
        would lazily allocate a row residency never sees (an untracked
        pool leak past max_resident)."""
        service, storm, seq_host, _mh, res = build_stack(tmp_path)
        conn = service.connect("a", lambda m: None)
        service.pump()
        drive(storm, "a", conn.client_id, 0)
        res.evict("a")
        assert "a" not in seq_host._rows
        service.disconnect("a", conn.client_id)
        service.pump()
        # Every live device row is accounted to the residency directory.
        assert set(seq_host._rows) <= set(res.resident)
        assert res.is_resident("a")
        res.evict("a")  # and the now-idle doc evicts cleanly again
        assert "a" not in seq_host._rows

    def test_per_op_submit_touches_and_hydrates(self, tmp_path):
        """The per-op path must refresh the idle clock (an ACTIVE doc
        must never idle-evict mid-session) and hydrate a cold doc into a
        TRACKED row — otherwise the orderer's deli submit would lazily
        allocate a blank row, regressing sequence numbers and corrupting
        the next cold head."""
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage, MessageType)
        clk = [0.0]
        service, storm, seq_host, _mh, res = build_stack(
            tmp_path, clock=lambda: clk[0], idle_evict_s=10.0)
        conn = service.connect("a", lambda m: None)
        service.pump()

        def per_op(i):
            service.submit("a", conn.client_id, [DocumentMessage(
                type=MessageType.OPERATION, contents={"op": i},
                client_sequence_number=i, reference_sequence_number=1)])
            service.pump()

        per_op(1)
        seq_before = seq_host.checkpoint("a").sequence_number
        # Active per-op traffic past the idle timeout: the touch keeps
        # the doc hot (evict_idle must find nothing).
        clk[0] = 12.0
        per_op(2)
        assert res.evict_idle() == []
        # Cold doc + per-op submit: hydrates tracked, sequence numbers
        # CONTINUE (no blank-row regression).
        res.evict("a")
        assert "a" not in seq_host._rows
        per_op(3)
        assert res.is_resident("a")
        assert set(seq_host._rows) <= set(res.resident)
        assert seq_host.checkpoint("a").sequence_number > seq_before

    def test_frame_wider_than_pool_nacks_terminal(self, tmp_path):
        """A frame naming more distinct docs than the pool holds can
        NEVER be admitted — the nack must be non-retryable (the
        wal-failed precedent), not a retry loop that cannot succeed."""
        service, storm, *_ , res = build_stack(tmp_path, max_resident=2)
        nacks = []
        entries = [[f"w{i}", f"client-{i}", 1, 1, K] for i in range(3)]
        payload = b"".join(set_words(0).tobytes() for _ in range(3))
        storm.submit_frame(nacks.append, {"rid": 1, "docs": entries},
                           memoryview(payload))
        assert nacks and nacks[0]["error"] == "frame-too-wide"
        assert nacks[0]["retryable"] is False

    def test_idle_evict_at_timeout(self, tmp_path):
        clk = [0.0]
        service, storm, *_ , res = build_stack(
            tmp_path, clock=lambda: clk[0], idle_evict_s=10.0)
        clients = connect_docs(service, ["a", "b"])
        drive(storm, "a", clients["a"], 0)
        clk[0] = 5.0
        drive(storm, "b", clients["b"], 0)
        clk[0] = 12.0  # a idle 12s, b idle 7s
        evicted = res.evict_idle()
        assert evicted == ["a"]
        assert not res.is_resident("a") and res.is_resident("b")
        clk[0] = 16.0
        assert res.evict_idle() == ["b"]
        assert res.resident == {}

    def test_rehydrate_byte_identical_vs_never_evicted_twin(self, tmp_path):
        """Snapshot + WAL-tail replay ≡ never-evicted twin: a stack whose
        pool holds ONE doc (every frame evicts the LRU and hydrates the
        target) must end byte-identical to a twin that never tiers."""
        docs = ["a", "b", "c"]
        churn = build_stack(tmp_path / "churn", max_resident=1)
        twin = build_stack(tmp_path / "twin", residency=False)
        digests = []
        for service, storm, seq_host, merge_host, res in (churn, twin):
            clients = connect_docs(service, docs)
            for r in range(4):
                for d in docs:
                    drive(storm, d, clients[d], r)
            if res is not None:
                assert res.stats["evictions"] >= 8  # genuinely churned
                assert res.stats["cold_hydrations"] >= 8
            digests.append(chaos._digest(service, storm, seq_host,
                                         merge_host, docs, residency=res))
        assert digests[0] == digests[1]

    def test_recover_trims_cold_docs_and_rehydrates(self, tmp_path):
        service, storm, seq_host, merge_host, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a", "b"])
        for r in range(2):
            for d in ("a", "b"):
                drive(storm, d, clients[d], r)
        res.evict("a")
        storm.checkpoint()
        want = chaos._digest(service, storm, seq_host, merge_host,
                             ["a", "b"], residency=res)

        # Process death: a fresh stack over the same durable directories.
        service2, storm2, seq2, merge2, res2 = build_stack(tmp_path)
        info = storm2.recover()
        assert info["restored_from"] is not None
        assert res2.is_resident("b")
        assert not res2.is_resident("a")  # stayed cold through recovery
        assert "a" not in storm2._doc_ticks  # RAM stays O(hot)
        got = chaos._digest(service2, storm2, seq2, merge2, ["a", "b"],
                            residency=res2)
        assert got == want
        assert res2.stats["cold_hydrations"] >= 1  # the digest hydrated a


class TestColdStoreGC:
    def test_superseded_cold_head_blobs_deleted_on_flip(self, tmp_path):
        """Round-13 satellite: re-evicting a churned doc releases the
        superseded cold head's unreferenced content-addressed blobs —
        a cold doc's disk cost stays ONE snapshot, not one per
        eviction — while chunks another doc's snapshot shares survive."""
        import os

        service, storm, seq_host, merge_host, res = build_stack(tmp_path)
        clients = connect_docs(service, ["g1", "g2"])

        def blob_count():
            n = 0
            for root, _dirs, files in os.walk(tmp_path / "git" / "objects"):
                n += len(files)
            return n

        drive(storm, "g1", clients["g1"], 0, words=set_words(0))
        h1 = res.evict("g1")
        blobs_one_head = blob_count()
        # Churn: hydrate, mutate, re-evict — the head flips and the
        # superseded snapshot's unique blobs delete.
        for r in range(1, 4):
            drive(storm, "g1", clients["g1"], r, words=set_words(r))
            h2 = res.evict("g1")
            assert h2 != h1
            h1 = h2
        assert storm.snapshots.get(COLD_KEY_PREFIX + "g1", h1) is not None
        # Disk stays O(one snapshot) per cold doc (+ tree object churn
        # tolerance), not O(evictions).
        assert blob_count() <= blobs_one_head + 2
        # The live head still hydrates byte-exactly.
        res.ensure_resident("g1", gate=False)
        assert res.stats["cold_hydrations"] >= 1

    def test_shared_chunks_survive_one_docs_release(self, tmp_path):
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        store = GitSnapshotStore(tmp_path / "gc")
        payload = {"planes": "z" * 200}
        ha = store.upload("__cold__::a", payload)
        hb = store.upload("__cold__::b", payload)
        store.set_head("__cold__::a", ha)
        store.set_head("__cold__::b", hb)
        assert ha != hb  # trees differ (doc id); the CHUNKS dedup
        ha2 = store.upload("__cold__::a", {"planes": "w"})
        store.set_head("__cold__::a", ha2)
        deleted = store.release("__cold__::a", ha)
        # Only a's superseded TREE deletes; the content chunk b's
        # snapshot shares survives and b still reads byte-exactly.
        assert deleted == [ha]
        assert store.get("__cold__::b", hb) == payload
        # Releasing the current head is refused outright.
        assert store.release("__cold__::b", hb) == []
        # Refcounts survive a reopen (the journal is the authority):
        # once b's head flips too, the LAST reference release deletes.
        store2 = GitSnapshotStore(tmp_path / "gc")
        hb2 = store2.upload("__cold__::b", {"planes": "w2"})
        store2.set_head("__cold__::b", hb2)
        assert len(store2.release("__cold__::b", hb)) > 0
        assert store2.get("__cold__::b", hb) is None
        assert store2.get("__cold__::b", hb2) == {"planes": "w2"}

    def test_idempotent_reupload_does_not_inflate_refcounts(self,
                                                            tmp_path):
        """Re-evicting an UNCHANGED doc re-uploads the identical
        snapshot (same handle, head never moves, caller skips release) —
        the refcount must not inflate, or the eventual real supersession
        could never delete it."""
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        store = GitSnapshotStore(tmp_path / "gci")
        h1 = store.upload("__cold__::y", {"v": "same"})
        store.set_head("__cold__::y", h1)
        for _ in range(3):  # unchanged re-evictions
            assert store.upload("__cold__::y", {"v": "same"}) == h1
            store.set_head("__cold__::y", h1)
        h2 = store.upload("__cold__::y", {"v": "changed"})
        store.set_head("__cold__::y", h2)
        deleted = store.release("__cold__::y", h1)
        assert len(deleted) == 2  # tree + chunk: the old head really GCs
        assert store.get("__cold__::y", h1) is None
        assert store.get("__cold__::y", h2) == {"v": "changed"}

    def test_release_deletes_across_refcount_compaction(self, tmp_path):
        """Regression: deletability is decided from PRE-decrement counts
        — a journal compaction triggered by the release's own decrement
        drops zeroed shas from the map, and reading counts afterwards
        mistook them for legacy-pinned objects (leaking forever)."""
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        store = GitSnapshotStore(tmp_path / "gcc")
        orig = store._journal_refs

        def journal_then_compact(sign, shas):
            orig(sign, shas)
            store._compact_refs()  # worst case: compact EVERY append

        store._journal_refs = journal_then_compact
        h1 = store.upload("__cold__::x", {"v": 1})
        store.set_head("__cold__::x", h1)
        h2 = store.upload("__cold__::x", {"v": 2})
        store.set_head("__cold__::x", h2)
        deleted = store.release("__cold__::x", h1)
        assert len(deleted) == 2  # tree + chunk deleted, not leaked
        assert store.get("__cold__::x", h1) is None
        assert store.get("__cold__::x", h2) == {"v": 2}


class TestRefusals:
    def test_quarantined_doc_pinned_resident(self, tmp_path):
        clk = [0.0]
        service, storm, *_, res = build_stack(
            tmp_path, clock=lambda: clk[0], idle_evict_s=10.0)
        clients = connect_docs(service, ["a", "b"])
        for d in ("a", "b"):
            drive(storm, d, clients[d], 0)
        storm.quarantined["a"] = {"reason": "test", "tick": 0}
        with pytest.raises(EvictionRefused):
            res.evict("a")
        clk[0] = 20.0
        assert res.evict_idle() == ["b"]  # a skipped: pinned resident
        assert res.is_resident("a")
        assert res.stats["evict_refusals"] >= 1

    def test_degraded_wal_refuses_eviction(self, tmp_path):
        service, storm, *_, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a"])
        drive(storm, "a", clients["a"], 0)
        storm._group_wal.breaker.record_failure()
        assert storm.wal_degraded
        with pytest.raises(EvictionRefused):
            res.evict("a")
        assert res.is_resident("a")
        storm._group_wal.breaker.record_success()
        res.evict("a")
        assert not res.is_resident("a")

    def test_full_pool_of_pinned_docs_busy_nacks(self, tmp_path):
        service, storm, *_, res = build_stack(tmp_path, max_resident=1)
        clients = connect_docs(service, ["a"])
        drive(storm, "a", clients["a"], 0)
        storm.quarantined["a"] = {"reason": "test", "tick": 0}
        nacks = []
        drive(storm, "b", "client-99", 0, push=nacks.append, rid=77)
        assert nacks and nacks[0]["error"] == "busy"
        assert nacks[0]["retry_after_s"] > 0
        assert not res.is_resident("b")


class TestCapacityAndAdmission:
    def test_lru_capacity_eviction(self, tmp_path):
        service, storm, seq_host, _mh, res = build_stack(
            tmp_path, max_resident=2)
        clients = connect_docs(service, ["a", "b"])
        drive(storm, "a", clients["a"], 0)
        drive(storm, "b", clients["b"], 0)
        # A third doc's frame must evict the LRU (a), not grow the pool.
        drive(storm, "c", "client-42", 0)
        assert res.is_resident("c") and res.is_resident("b")
        assert not res.is_resident("a")
        assert len(res.resident) == 2
        # Device rows recycled, not grown: the high-water mark is bounded
        # by the PEAK RESIDENT count, never the registered population.
        assert seq_host._row_count <= 2 + 1  # +1: c joined before a evicted

    def test_hydration_storm_is_admission_gated(self, tmp_path):
        clk = [0.0]
        service, storm, *_, res = build_stack(
            tmp_path, clock=lambda: clk[0],
            hydration_rate_per_s=1.0, hydration_burst=1.0)
        clients = connect_docs(service, ["a"])
        drive(storm, "a", clients["a"], 0)
        res.evict("a")
        res.evict_idle()  # no-op, just exercises the sweep guard

        # Burst=1: the first cold-doc frame hydrates, the second nacks
        # with the bucket's laddered retry hint.
        drive(storm, "a", clients["a"], 1)
        assert res.is_resident("a")
        nacks = []
        drive(storm, "b", "client-9", 0, push=nacks.append, rid=5)
        assert nacks and nacks[0]["error"] == "hydrating"
        retry = nacks[0]["retry_after_s"]
        assert retry > 0
        assert res.stats["hydration_nacks"] == 1

        # The refusal reserved a CLAIMABLE slot: returning at the hint
        # succeeds without re-debiting (no compounding retry debt).
        clk[0] += retry
        acks = []
        drive(storm, "b", "client-9", 0, push=acks.append, rid=6)
        assert acks and not acks[0].get("error")
        assert res.is_resident("b")

    def test_early_return_keeps_same_reservation(self, tmp_path):
        clk = [0.0]
        service, storm, *_, res = build_stack(
            tmp_path, clock=lambda: clk[0],
            hydration_rate_per_s=1.0, hydration_burst=1.0)
        retry0 = res.ensure_resident("x")
        assert retry0 is None  # burst token
        retry1 = res.ensure_resident("y")
        assert retry1 is not None
        # Coming back EARLY returns the remaining wait on the SAME slot
        # (no second debit against the bucket).
        clk[0] += retry1 / 2
        retry2 = res.ensure_resident("y")
        assert retry2 == pytest.approx(retry1 - retry1 / 2, abs=1e-6)
        clk[0] += retry2
        assert res.ensure_resident("y") is None


class TestBoundedBookkeeping:
    def test_doc_bookkeeping_stays_o_hot_under_churn(self, tmp_path):
        """Satellite: _doc_ticks / doc_tick_counts trim on eviction, so
        a churned many-doc run keeps them O(hot set) — never one entry
        per doc ever served."""
        hot = 4
        service, storm, seq_host, _mh, res = build_stack(
            tmp_path, num_docs=hot, max_resident=hot)
        n_docs = 48
        clients = {}
        for i in range(n_docs):
            doc = f"doc-{i}"
            clients[doc] = service.connect(doc, lambda m: None).client_id
            service.pump()
            drive(storm, doc, clients[doc], 0)
        assert res.stats["evictions"] >= n_docs - hot
        assert len(res.resident) == hot
        assert len(storm._doc_ticks) <= hot
        assert len(storm.doc_tick_counts) <= hot
        assert seq_host._row_count <= hot
        # The trimmed bookkeeping travels with the doc: re-hydrating an
        # early victim restores its tick index and telemetry count.
        drive(storm, "doc-0", clients["doc-0"], 1)
        assert storm.doc_tick_counts["doc-0"] == 2
        assert len(storm._doc_ticks["doc-0"]) == 2

    def test_doc_index_retention_horizon(self, tmp_path):
        service, storm, *_ , res = build_stack(
            tmp_path, storm_kw=dict(doc_index_retention_ticks=3))
        clients = connect_docs(service, ["a"])
        for r in range(8):
            drive(storm, "a", clients["a"], r)
        ticks = storm._doc_ticks["a"]
        assert len(ticks) <= 4  # horizon + the tick that triggered it
        assert ticks[-1][2] == storm._tick_counter - 1
        assert all(t[2] >= storm._tick_counter - 1 - 3 for t in ticks)


class TestCohortCache:
    def test_cohort_cache_is_bounded_lru_with_counters(self, tmp_path):
        """Satellite: residency churn alternates cohorts; the old
        single-entry cache thrashed every tick. The bounded LRU keeps
        each live cohort warm and exports hit/miss counters."""
        service, storm, _sh, merge_host, _res = build_stack(
            tmp_path, residency=False)
        clients = connect_docs(service, ["a", "b"])
        # Alternate two single-doc cohorts — the single-entry cache
        # would miss every round.
        for r in range(4):
            for d in ("a", "b"):
                drive(storm, d, clients[d], r)
        snap = merge_host.metrics.snapshot()
        assert snap["storm.cohort_cache.misses"] == 2  # one per cohort
        assert snap["storm.cohort_cache.hits"] >= 6
        assert len(storm._cohort_cache) <= storm._cohort_cache.capacity


class TestCountedLRU:
    def test_bound_and_recency(self):
        lru = CountedLRU(2)
        lru.put("a", 1)
        lru.put("b", 2)
        assert lru.get("a") == 1  # a now most-recent
        lru.put("c", 3)  # evicts b (LRU)
        assert "b" not in lru
        assert lru.get("b") is None
        assert lru.get("a") == 1 and lru.get("c") == 3
        assert len(lru) == 2

    def test_counters_reach_registry(self):
        reg = MetricsRegistry()
        lru = CountedLRU(4, registry=reg, prefix="t.lru")
        lru.put("k", "v")
        lru.get("k")
        lru.get("missing")
        snap = reg.snapshot()
        assert snap["t.lru.hits"] == 1 and snap["t.lru.misses"] == 1
        assert lru.hits == 1 and lru.misses == 1

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            CountedLRU(0)


class TestRowRecycling:
    def test_sequencer_rows_recycle(self, tmp_path):
        seq = KernelSequencerHost(num_slots=2, initial_capacity=2)
        service = RouterliciousService(batched_deli_host=seq,
                                       auto_pump=False)
        for d in ("a", "b"):
            service.connect(d, lambda m: None)
        service.pump()
        assert seq._row_count == 2
        gen = seq.membership_gen
        row_a = seq._rows["a"]
        cp = seq.checkpoint("b")
        seq.release_doc("a")
        assert seq.membership_gen > gen  # stale cohorts invalidated
        assert seq._free_rows == [row_a]
        # The freed row reissues before the high-water mark grows.
        service.connect("c", lambda m: None)
        service.pump()
        assert seq._rows["c"] == row_a
        assert seq._row_count == 2
        # The surviving doc's planes are untouched.
        assert dataclasses.asdict(seq.checkpoint("b")) == \
            dataclasses.asdict(cp)

    def test_released_row_is_blank(self, tmp_path):
        service, storm, seq_host, merge_host, res = build_stack(tmp_path)
        clients = connect_docs(service, ["a"])
        drive(storm, "a", clients["a"], 0)
        row = seq_host._rows["a"]
        res.evict("a")
        # Device planes at the recycled index equal init defaults — a
        # stale clientSeq table would poison the next tenant's dedup.
        import fluidframework_tpu.ops.sequencer as seqk
        blank = seqk.init_state(1, seq_host._alloc_slots + 1)
        for f in type(seq_host._state)._fields:
            got = np.asarray(getattr(seq_host._state, f))[row]
            want = np.asarray(getattr(blank, f))[0]
            assert np.array_equal(got, want), f
