"""Donated-tick compile-cache bypass (utils/compile_cache.bypass):
jaxlib 0.4.37 double-frees donated buffers on the SECOND run of an
executable deserialized from the persistent cache, so every donated
serving tick compiles through `uncached`. These tests pin the
workaround so a jax upgrade cannot silently regress it (ADVICE item 1):
the bypass must actually suppress persistent-cache use, the guarded
donated tick must run repeatedly with identical results, and the
fail-closed warning must NOT fire on this jax version — when jax's
internals move, the warning test fails loudly and the double-free needs
re-auditing."""

import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.utils import compile_cache


def donated_tick():
    @functools.partial(jax.jit, donate_argnums=(0,))
    def tick(state, words):
        return state + jnp.sum(words), state * 0 + words
    return compile_cache.uncached(tick)


def test_cache_round_tripped_donated_tick_runs_twice_identically():
    """The serving shape: a donated jit executed repeatedly under the
    bypass (cache enabled process-wide by conftest). Two executions of
    the same executable — exactly the shape that double-freed — must
    succeed with identical results."""
    tick = donated_tick()
    w = jnp.arange(8, dtype=jnp.int32)
    s1, out1 = tick(jnp.zeros(8, jnp.int32), w)
    s2, out2 = tick(s1, w)  # second run of the SAME executable
    s3, out3 = tick(s2, w)
    assert np.array_equal(np.asarray(out1), np.asarray(out3))
    assert np.asarray(s3)[0] == 3 * 28
    # The undonated re-jit escape hatch (bench uses it) stays reachable.
    assert callable(tick.__wrapped__)


def test_bypass_actually_suppresses_persistent_cache():
    """While inside bypass(), jax's per-compile gate must report the
    persistent cache unused; outside, the process-wide enable() state is
    restored untouched."""
    cc = pytest.importorskip("jax._src.compilation_cache")
    before = (cc._cache_checked, cc._cache_used)
    with compile_cache.bypass():
        assert (cc._cache_checked, cc._cache_used) == (True, False)
    assert (cc._cache_checked, cc._cache_used) == before


def test_bypass_does_not_fail_closed_on_this_jax():
    """The fail-closed path (jax internals moved → disable the cache
    process-wide + warn) must NOT trigger today. When jax moves and this
    fails, re-audit the donated-executable double-free before removing
    the bypass (ADVICE item 1)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        with compile_cache.bypass():
            pass


def test_sequencer_and_storm_ticks_are_wrapped():
    """The REAL donated serving ticks stay behind the bypass wrapper."""
    from fluidframework_tpu.server import kernel_host, storm

    for fn in (storm._storm_tick, storm._mixed_tick,
               kernel_host._step_one):
        assert getattr(fn, "__wrapped__", None) is not None, fn
