"""Donated-tick compile-cache bypass (utils/compile_cache.bypass):
jaxlib 0.4.37 double-frees donated buffers on the SECOND run of an
executable deserialized from the persistent cache, so every donated
serving tick compiles through `uncached`. These tests pin the
workaround so a jax upgrade cannot silently regress it (ADVICE item 1):
the bypass must actually suppress persistent-cache use, the guarded
donated tick must run repeatedly with identical results, and the
fail-closed warning must NOT fire on this jax version — when jax's
internals move, the warning test fails loudly and the double-free needs
re-auditing."""

import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.utils import compile_cache


def donated_tick():
    @functools.partial(jax.jit, donate_argnums=(0,))
    def tick(state, words):
        return state + jnp.sum(words), state * 0 + words
    return compile_cache.uncached(tick)


def test_cache_round_tripped_donated_tick_runs_twice_identically():
    """The serving shape: a donated jit executed repeatedly under the
    bypass (cache enabled process-wide by conftest). Two executions of
    the same executable — exactly the shape that double-freed — must
    succeed with identical results."""
    tick = donated_tick()
    w = jnp.arange(8, dtype=jnp.int32)
    s1, out1 = tick(jnp.zeros(8, jnp.int32), w)
    s2, out2 = tick(s1, w)  # second run of the SAME executable
    s3, out3 = tick(s2, w)
    assert np.array_equal(np.asarray(out1), np.asarray(out3))
    assert np.asarray(s3)[0] == 3 * 28
    # The undonated re-jit escape hatch (bench uses it) stays reachable.
    assert callable(tick.__wrapped__)


def test_bypass_actually_suppresses_persistent_cache():
    """While inside bypass(), jax's per-compile gate must report the
    persistent cache unused; outside, the process-wide enable() state is
    restored untouched."""
    cc = pytest.importorskip("jax._src.compilation_cache")
    before = (cc._cache_checked, cc._cache_used)
    with compile_cache.bypass():
        assert (cc._cache_checked, cc._cache_used) == (True, False)
    assert (cc._cache_checked, cc._cache_used) == before


def test_bypass_does_not_fail_closed_on_this_jax():
    """The fail-closed path (jax internals moved → disable the cache
    process-wide + warn) must NOT trigger today. When jax moves and this
    fails, re-audit the donated-executable double-free before removing
    the bypass (ADVICE item 1)."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        with compile_cache.bypass():
            pass


def test_sequencer_and_storm_ticks_are_wrapped():
    """The REAL donated serving ticks stay behind the bypass wrapper."""
    from fluidframework_tpu.server import kernel_host, storm

    for fn in (storm._storm_tick, storm._mixed_tick,
               kernel_host._step_one):
        assert getattr(fn, "__wrapped__", None) is not None, fn


def test_donated_jit_registry_is_audited():
    """ADVICE §1 re-audit guard, round-15 edition: the compile-cache
    bypass must cover EVERY donated serving tick — including any new
    sharded/combiner entry points a later round adds. The set of source
    files declaring ``donate_argnums`` is pinned here; a new donated jit
    in a new module fails this test until it is wrapped in
    ``compile_cache.uncached`` and added to both lists (the double-free
    was jaxlib-version-dependent — new tick functions must not silently
    re-enter the persistent cache). The round-15 mega-doc tier
    deliberately adds NO donated device entry points: the sequence-
    parallel merge kernel (ops/mergetree_sharded.py) is undonated and
    the doc combiner is host-side scalar work."""
    import pathlib

    import fluidframework_tpu

    root = pathlib.Path(fluidframework_tpu.__file__).parent
    files = {p.relative_to(root).as_posix()
             for p in root.rglob("*.py")
             if "donate_argnums" in p.read_text()}
    assert files == {"server/kernel_host.py", "server/storm.py"}, (
        "new donate_argnums site(s) — wrap them in "
        f"compile_cache.uncached and pin them here: {sorted(files)}")
    # And every known donated entry point IS wrapped (incl. the ones
    # new round-15 code paths dispatch through).
    from fluidframework_tpu.ops import mergetree_sharded as mts
    from fluidframework_tpu.server import kernel_host, storm

    for fn in (storm._storm_tick, storm._mixed_tick,
               kernel_host._step_one):
        assert getattr(fn, "__wrapped__", None) is not None, fn
    # The sharded kernel's tick is jit WITHOUT donation — the cache is
    # safe for it by the bypass docstring's own analysis; donation being
    # added there later would flip the file-set assertion above.
    assert "donate_argnums" not in pathlib.Path(
        mts.__file__).read_text()
