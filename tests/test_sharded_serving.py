"""Multi-host serving assembly (parallel/serving.py): simulated host
processes own contiguous doc ranges, feed one mesh-sharded fused
deli+merger tick, and harvest only their own rows — the
partitionManager.ts scale-out shape over a jax Mesh."""

import jax
import numpy as np
import pytest

from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.parallel.serving import ShardedServing


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= 8, "conftest provisions a virtual 8-device mesh"
    return make_mesh(devices[:8])


def test_hosts_own_disjoint_contiguous_ranges(mesh):
    serving = ShardedServing(mesh, num_docs=32, k=4, num_hosts=4)
    covered = []
    for port in serving.hosts:
        covered.extend(range(port.start, port.stop))
        assert serving.route(port.start).host_id == port.host_id
    assert covered == list(range(32))


def test_sharded_tick_matches_unsharded_reference(mesh):
    """Bit-identical map state: the same op stream through (a) the
    sharded multi-host serving loop and (b) a single-device run."""
    num_docs, k = 16, 8
    rng = np.random.default_rng(0)
    stream = {row: (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12
                    | (row % 8) << 2)
              for row in range(num_docs)}

    serving = ShardedServing(mesh, num_docs=num_docs, k=k, num_hosts=2)
    serving.join_all()
    for row, words in stream.items():
        serving.submit(row, words, first_cseq=1)
    harvest = serving.tick()
    assert all(n == k for rows in harvest.values()
               for (n, _f, _l) in rows.values())

    single = ShardedServing(make_mesh(jax.devices()[:1]),
                            num_docs=num_docs, k=k, num_hosts=1)
    single.join_all()
    for row, words in stream.items():
        single.submit(row, words, first_cseq=1)
    single.tick()
    assert np.array_equal(serving.map_rows(), single.map_rows())
    assert np.array_equal(np.asarray(serving.seq_state.seq),
                          np.asarray(single.seq_state.seq))


def test_harvest_is_shard_local_and_outputs_sharded(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=2)
    serving.join_all()
    words = np.full(4, 5 << 12, np.uint32)
    for row in range(16):
        serving.submit(row, words, first_cseq=1)
    harvest = serving.tick()
    for port in serving.hosts:
        assert set(harvest[port.host_id]) \
            == set(range(port.start, port.stop))
    devices = {s.device
               for s in serving.map_state.value.addressable_shards}
    assert len(devices) == 8


def test_foreign_row_submission_rejected(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=2)
    with pytest.raises(KeyError):
        serving.route(99)
    serving.submit(3, np.zeros(2, np.uint32), first_cseq=1)
    with pytest.raises(ValueError, match="already pending"):
        serving.submit(3, np.zeros(2, np.uint32), first_cseq=3)


def test_kernel_dedup_across_sharded_ticks(mesh):
    """At-least-once delivery: a host resending its tick verbatim gets
    everything IGNORED by the sharded sequencer (clientSeq dedup)."""
    serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=2)
    serving.join_all()
    words = np.full(4, 9 << 12, np.uint32)
    for row in range(8):
        serving.submit(row, words, first_cseq=1)
    first = serving.tick()
    for row in range(8):
        serving.submit(row, words, first_cseq=1)  # verbatim resend
    second = serving.tick(now=3)
    for port in serving.hosts:
        for row in range(port.start, port.stop):
            assert first[port.host_id][row][0] == 4
            assert second[port.host_id][row][0] == 0  # all duplicates


def test_global_metrics_psum(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=4)
    serving.join_all()
    for row in range(16):
        serving.submit(row, np.full(4, 2 << 12, np.uint32), first_cseq=1)
    serving.tick()
    metrics = serving.global_metrics()
    assert metrics["seq"] == 16 * 5  # join + 4 ops per doc
    assert metrics["present"] == 16


def test_host_kill_resume_rebalance(mesh):
    """Serving-host failover (VERDICT r3 item 6): checkpoint host 1,
    keep serving (durable log grows past the checkpoint), kill it, hand
    its doc range to host 0, restore from checkpoint + durable-log
    replay — no sequence regression, converged map rows, and the next
    tick continues seq assignment exactly where the log ended."""
    rng = np.random.default_rng(5)
    num_docs, k = 16, 8

    def words_for(row, t):
        slots = (np.arange(k) + t) % 8
        vals = 1000 * (t + 1) + row * 10 + np.arange(k)
        return ((slots.astype(np.uint32) << 2)
                | (vals.astype(np.uint32) << 12)).astype(np.uint32)

    serving = ShardedServing(mesh, num_docs=num_docs, k=k, num_hosts=2)
    serving.join_all()
    # Ticks 0-1: full traffic on every row.
    for t in range(2):
        for row in range(num_docs):
            serving.submit(row, words_for(row, t), first_cseq=1 + t * k)
        serving.tick()
    cp = serving.checkpoint_host(1)
    # Tick 2: more traffic AFTER the checkpoint (the durable tail).
    for row in range(num_docs):
        serving.submit(row, words_for(row, 2), first_cseq=1 + 2 * k)
    serving.tick()
    final_rows = serving.map_rows().copy()
    final_seq = np.asarray(serving.seq_state.seq).copy()
    durable = serving.durable

    # The replacement assembly: host 1 is dead; host 0 owns everything.
    revived = ShardedServing(mesh, num_docs=num_docs, k=k, num_hosts=2)
    revived.join_all()
    # Host 0's rows re-run the full log (its own recovery, offset 0);
    # host 1's rows restore from the checkpoint + tail replay.
    revived.rebalance_from(1, 0)
    assert revived.route(num_docs - 1).host_id == 0
    # host 0 replay from scratch (its durable log, offset 0):
    for t in range(3):
        for row in range(0, 8):
            revived.submit(row, words_for(row, t), first_cseq=1 + t * k)
        revived.tick()
    # host 1 rows: checkpoint + tail.
    revived.restore_host(cp, durable, serving._durable_base)

    got_rows = revived.map_rows()
    got_seq = np.asarray(revived.seq_state.seq)
    assert np.array_equal(got_seq, final_seq), (got_seq, final_seq)
    assert np.array_equal(got_rows, final_rows)

    # Continued service: the next tick's first seq extends the history.
    for row in range(num_docs):
        revived.submit(row, words_for(row, 3), first_cseq=1 + 3 * k)
    harvest = revived.tick()
    merged = {**harvest[0], **harvest[1]}
    for row in range(num_docs):
        n_seq, first, last = merged[row]
        assert n_seq == k
        assert first == final_seq[row] + 1, (row, first, final_seq[row])


def test_durable_log_trims_to_checkpoint_horizon(mesh):
    """Log retention: after checkpointing, records below the horizon are
    retired (bounded host memory); restores against the trimmed prefix
    fail loudly, restores from the checkpoint still replay exactly."""
    serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=1)
    serving.join_all()
    words = np.array([(1 << 12) | (0 << 2), (2 << 12) | (1 << 2),
                      (3 << 12) | (2 << 2), (4 << 12) | (3 << 2)],
                     np.uint32)
    for t in range(3):
        for r in range(8):
            serving.submit(r, words, first_cseq=1 + t * 4)
        serving.tick()
    cp = serving.checkpoint_host(0)
    for r in range(8):
        serving.submit(r, words, first_cseq=13)
    serving.tick()
    assert serving.durable_offset(0) == 4
    serving.trim_durable(cp["log_offsets"])
    assert len(serving.durable[0]) == 1  # only the post-checkpoint tick
    assert serving.durable_offset(0) == 4  # absolute cursor unmoved

    want_seq = np.asarray(serving.seq_state.seq).copy()
    revived = ShardedServing(mesh, num_docs=8, k=4, num_hosts=1)
    revived.join_all()
    revived.restore_host(cp, serving.durable, serving._durable_base)
    assert np.array_equal(np.asarray(revived.seq_state.seq), want_seq)

    # A checkpoint OLDER than the horizon must refuse, not corrupt.
    stale = dict(cp, log_offsets={r: 0 for r in range(8)})
    third = ShardedServing(mesh, num_docs=8, k=4, num_hosts=1)
    third.join_all()
    with pytest.raises(ValueError):
        third.restore_host(stale, serving.durable, serving._durable_base)


def test_durable_retention_bounds_log_without_checkpoints(mesh):
    """An assembly nobody checkpoints must not grow its durable log with
    total history: automatic retention keeps the tail bounded and the
    absolute offsets consistent."""
    serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=1,
                             durable_retention_ticks=5)
    serving.join_all()
    words = np.array([(7 << 12)], np.uint32)
    for t in range(12):
        serving.submit(0, words, first_cseq=1 + t)
        serving.tick()
    assert len(serving.durable[0]) == 5
    assert serving.durable_offset(0) == 12
    assert serving._durable_base[0] == 7


def test_shard_residency_oversubscribed_churn(mesh):
    """ShardResidency (ISSUE 9): a registered doc population 5x the
    device row pool serves through hydrate/evict churn with every doc's
    converged value preserved, resident count bounded by the pool, and
    idle shrink freeing rows."""
    from fluidframework_tpu.parallel.serving import ShardResidency

    num_rows = 4
    serving = ShardedServing(make_mesh(jax.devices()[:1]),
                             num_docs=num_rows, k=4, num_hosts=2,
                             num_clients=2, map_slots=8)
    res = ShardResidency(serving, join_slots=(0,))
    docs = [f"doc-{i}" for i in range(5 * num_rows)]
    want = {}
    for rnd in range(2):
        for i, doc in enumerate(docs):
            row = res.resolve(doc)
            assert serving.hosts[res.host_for(doc)].owns(row)
            value = (rnd * 37 + i) % 97 + 1
            words = np.array(
                [np.uint32(value) << 12 | np.uint32(1) << 2], np.uint32)
            serving.submit(row, words, first_cseq=rnd + 1)
            serving.tick()
            want[doc] = value
    assert res.resident_count() <= num_rows
    assert res.stats["evictions"] > 0
    assert res.stats["cold_hydrations"] > 0
    # Every doc's value survived its evict/re-hydrate round trips.
    for doc in docs:
        row = res.resolve(doc)
        got = int(np.asarray(serving.map_state.value)[row, 1])
        assert got == want[doc], doc
    # Idle shrink: one resident per host, rows recycled to the free list.
    res.evict_idle(keep_per_host=1)
    assert res.resident_count() <= 2
    assert sum(len(f) for f in res._free.values()) >= num_rows - 2


def test_shard_residency_refuses_pending_evict(mesh):
    from fluidframework_tpu.parallel.serving import ShardResidency

    serving = ShardedServing(make_mesh(jax.devices()[:1]), num_docs=2,
                             k=4, num_hosts=1, map_slots=8)
    res = ShardResidency(serving)
    row = res.resolve("doc-a")
    serving.submit(row, np.array([(5 << 12) | (1 << 2)], np.uint32),
                   first_cseq=1)
    with pytest.raises(ValueError):
        res.evict("doc-a")
    serving.tick()
    res.evict("doc-a")  # settles after the tick
    assert not res.is_resident("doc-a")


def test_shard_residency_resolve_skips_pending_victims(mesh):
    """A full host range with a pending-submission LRU resident must
    evict the next evictable doc, not crash on the pinned one."""
    from fluidframework_tpu.parallel.serving import ShardResidency

    serving = ShardedServing(make_mesh(jax.devices()[:1]), num_docs=2,
                             k=4, num_hosts=1, map_slots=8)
    res = ShardResidency(serving)
    row_a = res.resolve("doc-a")  # LRU after doc-b resolves
    res.resolve("doc-b")
    serving.submit(row_a, np.array([(5 << 12) | (1 << 2)], np.uint32),
                   first_cseq=1)
    row_c = res.resolve("doc-c")  # must evict doc-b, not doc-a
    assert res.is_resident("doc-a") and res.is_resident("doc-c")
    assert not res.is_resident("doc-b")
    assert row_c != row_a


def test_megadoc_lanes_match_single_row_twin(mesh):
    """Lane placement in the serving assembly (ISSUE 12): one logical
    doc spread over 4 ROWS (device lanes) through the doc-space
    combiner must converge byte-identically — entries AND doc-seq ack
    quads — with a single-row twin serving the same writer batches
    sequentially, while dup resends and gap batches never touch a
    lane."""
    from fluidframework_tpu.parallel.serving import MegaDocLanes

    k, writers = 6, 6
    serving = ShardedServing(mesh, num_docs=8, k=k, num_hosts=1,
                             num_clients=4, map_slots=16)
    serving.join_all(slots=list(range(4)))
    lanes = MegaDocLanes(serving, lane_rows=[0, 1, 2, 3])

    twin = ShardedServing(mesh, num_docs=8, k=k, num_hosts=1,
                          num_clients=writers + 1, map_slots=16)
    twin.join_all(slots=list(range(writers)))
    # Writers join up front on BOTH sides (each join revs the doc seq).
    for w in range(writers):
        lanes.join(f"writer-{w}")

    rng = np.random.default_rng(42)
    cseqs = {w: 1 for w in range(writers)}
    prev = {}
    mega_acks, twin_acks = [], []
    twin_seq = 0
    for r in range(4):
        for w in range(writers):
            client = f"writer-{w}"
            action = rng.choice(["fresh", "fresh", "dup", "gap"])
            words = (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12
                     | (rng.integers(0, 16, k).astype(np.uint32) << 2))
            if action == "dup" and w in prev:
                cseq0, words = prev[w]
            elif action == "gap":
                cseq0 = cseqs[w] + 3
            else:
                cseq0 = cseqs[w]
                cseqs[w] += k
                prev[w] = (cseq0, words)
            dec = lanes.submit(client, words, cseq0, ref_seq=1)
            mega_acks.append((r, w, dec.n_seq, dec.first, dec.last))
            # Twin: the same batch on ONE row, its own tick (the
            # single-lane shape), writer = its own client slot.
            h = twin.submit(0, words, cseq0, ref_seq=1, client_slot=w)
            harvest = twin.tick()
            n_ok, first, last = harvest[0][0]
            twin_seq = last if n_ok else twin_seq
            twin_acks.append((r, w, n_ok,
                              first if n_ok else 2**31 - 1, last))
        serving.flush()
    twin.flush()
    serving.flush()
    assert mega_acks == twin_acks
    twin_vals = {s: int(v) for s, v in enumerate(
        np.asarray(twin.map_state.value[0]))
        if np.asarray(twin.map_state.present[0])[s]}
    assert lanes.entries() == twin_vals
    # The lanes really spread the doc: >1 row holds sequenced state.
    active_rows = {row for row in lanes.rows
                   if int(np.asarray(serving.seq_state.seq[row])) > 0}
    assert len(active_rows) > 1


def test_shard_residency_live_migration_2_to_4_hosts(mesh):
    """Live placement in the device-lane tier (ISSUE 13): genesis on 2
    of 4 host ranges, activation + PlacementController rebalance moves
    docs via the cold-record carrier — every value preserved, every
    doc's row inside its NEW owner's range, genesis hashes never
    silently re-route."""
    from fluidframework_tpu.parallel.placement import PlacementController
    from fluidframework_tpu.parallel.serving import ShardResidency

    serving = ShardedServing(make_mesh(jax.devices()[:1]), num_docs=8,
                             k=4, num_hosts=4, map_slots=8)
    res = ShardResidency(serving, active_hosts=(0, 1))
    docs = [f"doc-{i}" for i in range(8)]
    want = {}
    for i, doc in enumerate(docs):
        row = res.resolve(doc)
        assert res.host_for(doc) in (0, 1)
        value = 10 + i
        serving.submit(row, np.array([(value << 12) | (1 << 2)],
                                     np.uint32), first_cseq=1)
        serving.tick()
        want[doc] = value
    serving.flush()
    before = {d: res.host_for(d) for d in docs}
    res.activate_host(2)
    res.activate_host(3)
    # Activation alone must not re-route anything (sticky genesis).
    assert {d: res.host_for(d) for d in docs} == before
    ctrl = PlacementController(res, max_moves_per_round=8)
    report = ctrl.rebalance()
    assert report["converged"], report
    assert set(report["docs_per_host"]) == {0, 1, 2, 3}
    assert res.stats["migrations"] >= 2
    assert len(res.blackouts_s) == res.stats["migrations"]
    for doc in docs:
        row = res.resolve(doc)
        assert serving.hosts[res.host_for(doc)].owns(row)
        got = int(np.asarray(serving.map_state.value)[row, 1])
        assert got == want[doc], doc


def test_shard_residency_migrate_refuses_pending_and_rolls_on(mesh):
    """A doc with a pending (unticked) submission refuses migration —
    tick first, then the move succeeds and serving resumes on the new
    host with the doc's cseq dedup intact."""
    from fluidframework_tpu.parallel.serving import ShardResidency

    serving = ShardedServing(make_mesh(jax.devices()[:1]), num_docs=4,
                             k=4, num_hosts=2, map_slots=8)
    res = ShardResidency(serving)
    doc = "doc-a"
    row = res.resolve(doc)
    src = res.host_for(doc)
    dst = 1 - src
    serving.submit(row, np.array([(5 << 12) | (1 << 2)], np.uint32),
                   first_cseq=1)
    with pytest.raises(ValueError):
        res.migrate(doc, dst)
    serving.tick()
    serving.flush()
    new_row = res.migrate(doc, dst)
    assert res.host_for(doc) == dst
    assert serving.hosts[dst].owns(new_row)
    # Dedup survives the move: a verbatim resend sequences zero ops.
    serving.submit(new_row, np.array([(5 << 12) | (1 << 2)], np.uint32),
                   first_cseq=1)
    harvest = serving.tick()
    serving.flush()
    assert int(np.asarray(serving.map_state.value)[new_row, 1]) == 5
