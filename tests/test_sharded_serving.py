"""Multi-host serving assembly (parallel/serving.py): simulated host
processes own contiguous doc ranges, feed one mesh-sharded fused
deli+merger tick, and harvest only their own rows — the
partitionManager.ts scale-out shape over a jax Mesh."""

import jax
import numpy as np
import pytest

from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.parallel.serving import ShardedServing


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= 8, "conftest provisions a virtual 8-device mesh"
    return make_mesh(devices[:8])


def test_hosts_own_disjoint_contiguous_ranges(mesh):
    serving = ShardedServing(mesh, num_docs=32, k=4, num_hosts=4)
    covered = []
    for port in serving.hosts:
        covered.extend(range(port.start, port.stop))
        assert serving.route(port.start).host_id == port.host_id
    assert covered == list(range(32))


def test_sharded_tick_matches_unsharded_reference(mesh):
    """Bit-identical map state: the same op stream through (a) the
    sharded multi-host serving loop and (b) a single-device run."""
    num_docs, k = 16, 8
    rng = np.random.default_rng(0)
    stream = {row: (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12
                    | (row % 8) << 2)
              for row in range(num_docs)}

    serving = ShardedServing(mesh, num_docs=num_docs, k=k, num_hosts=2)
    serving.join_all()
    for row, words in stream.items():
        serving.submit(row, words, first_cseq=1)
    harvest = serving.tick()
    assert all(n == k for rows in harvest.values()
               for (n, _f, _l) in rows.values())

    single = ShardedServing(make_mesh(jax.devices()[:1]),
                            num_docs=num_docs, k=k, num_hosts=1)
    single.join_all()
    for row, words in stream.items():
        single.submit(row, words, first_cseq=1)
    single.tick()
    assert np.array_equal(serving.map_rows(), single.map_rows())
    assert np.array_equal(np.asarray(serving.seq_state.seq),
                          np.asarray(single.seq_state.seq))


def test_harvest_is_shard_local_and_outputs_sharded(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=2)
    serving.join_all()
    words = np.full(4, 5 << 12, np.uint32)
    for row in range(16):
        serving.submit(row, words, first_cseq=1)
    harvest = serving.tick()
    for port in serving.hosts:
        assert set(harvest[port.host_id]) \
            == set(range(port.start, port.stop))
    devices = {s.device
               for s in serving.map_state.value.addressable_shards}
    assert len(devices) == 8


def test_foreign_row_submission_rejected(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=2)
    with pytest.raises(KeyError):
        serving.route(99)
    serving.submit(3, np.zeros(2, np.uint32), first_cseq=1)
    with pytest.raises(ValueError, match="already pending"):
        serving.submit(3, np.zeros(2, np.uint32), first_cseq=3)


def test_kernel_dedup_across_sharded_ticks(mesh):
    """At-least-once delivery: a host resending its tick verbatim gets
    everything IGNORED by the sharded sequencer (clientSeq dedup)."""
    serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=2)
    serving.join_all()
    words = np.full(4, 9 << 12, np.uint32)
    for row in range(8):
        serving.submit(row, words, first_cseq=1)
    first = serving.tick()
    for row in range(8):
        serving.submit(row, words, first_cseq=1)  # verbatim resend
    second = serving.tick(now=3)
    for port in serving.hosts:
        for row in range(port.start, port.stop):
            assert first[port.host_id][row][0] == 4
            assert second[port.host_id][row][0] == 0  # all duplicates


def test_global_metrics_psum(mesh):
    serving = ShardedServing(mesh, num_docs=16, k=4, num_hosts=4)
    serving.join_all()
    for row in range(16):
        serving.submit(row, np.full(4, 2 << 12, np.uint32), first_cseq=1)
    serving.tick()
    metrics = serving.global_metrics()
    assert metrics["seq"] == 16 * 5  # join + 4 ops per doc
    assert metrics["present"] == 16
