"""History plane (round 18): time-travel reads, named branches, and
summarization compaction (server/history.py).

The acceptance bars under test:

* **materialize-at-N ≡ replay-to-N** — for EVERY seq of a fuzzed op
  stream, ``read_at(doc, s)`` equals a naive sequential replay of the
  materialized deltas to ``s`` (and at the head, the DEVICE row);
* **fork at N ≡ replay-to-N** — the branch's seeded device planes are
  byte-identical to the parent's planes captured at N;
* **compaction never changes state** — a compacting/trimming plane
  serves every still-addressable read byte-identical to a
  never-compacted twin, survives restart over the trimmed WAL, and
  genuinely shrinks the spill file;
* **merge-back determinism** — branch deltas re-submitted through the
  ordinary sequencer converge identically across runs, concurrent
  parent head writes included.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.history import (
    HistoryError,
    HistoryPlane,
)
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.residency import ResidencyManager
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController

K = 8


def _stack(root, residency=False, spill=True, **hist_kw):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=8)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False,
                                   idle_check_interval=10**9)
    kw: dict = {}
    if spill:
        kw.update(spill_dir=str(root / "spill"), durability="group")
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=10**9, pipeline_depth=0,
                            snapshots=GitSnapshotStore(str(root / "git")),
                            **kw)
    hist = HistoryPlane(storm, **hist_kw)
    res = None
    if residency:
        res = ResidencyManager(storm, idle_evict_s=1e9,
                               hydration_rate_per_s=1e9)
    return service, storm, hist, res


def _close(storm):
    if storm._group_wal is not None:
        storm._group_wal.close()


def _words(seed, r, i, k=K, clears=True):
    rng = np.random.default_rng([seed, r, i])
    kinds = rng.choice([0, 0, 0, 1, 2] if clears else [0, 0, 0, 1],
                       size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _serve(service, storm, docs, rounds, seed=7, clears=True,
           checkpoint_first=True):
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in docs}
    service.pump()
    if checkpoint_first and storm.snapshots is not None:
        storm.checkpoint()
    for r in range(rounds):
        for i, d in enumerate(docs):
            storm.submit_frame(
                None, {"rid": (r, d),
                       "docs": [[d, clients[d], 1 + r * K, 1, K]]},
                memoryview(_words(seed, r, i, clears=clears).tobytes()))
        storm.flush()
    return clients


def _naive_prefixes(service, doc):
    """{seq: entries-after-applying-ops-through-seq} from the
    materialized delta stream — the reference fold read_at must match
    at EVERY seq."""
    from fluidframework_tpu.protocol.messages import MessageType
    by_seq = {}
    for m in service.get_deltas(doc, 0):
        if m.type == MessageType.OPERATION:
            by_seq[m.sequence_number] = \
                m.contents["contents"]["contents"]
    head = max(by_seq, default=0)
    state: dict = {}
    out = {0: {}}
    for s in range(1, head + 1):
        c = by_seq.get(s)
        if c is not None:
            if c["type"] == "set":
                state[c["key"]] = c["value"]
            elif c["type"] == "delete":
                state.pop(c["key"], None)
            else:
                state.clear()
        out[s] = dict(state)
    return out


class TestTimeTravel:
    def test_materialize_at_n_equals_replay_to_n_every_seq(self,
                                                           tmp_path):
        """The differential bar: for every seq of a fuzzed stream
        (sets/deletes/clears), read_at's scalar fold equals the naive
        sequential replay — and at the head, the device row."""
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=6)
        ref = _naive_prefixes(service, "d0")
        head = hist.head_seq("d0")
        assert head == max(ref)
        for s in range(0, head + 1):
            got = hist.read_at("d0", s)["entries"]
            assert got == ref[s], (s, got, ref[s])
        assert hist.read_at("d0", head)["entries"] == \
            storm.merge_host.map_entries("d0", storm.datastore,
                                         storm.channel)
        _close(storm)

    def test_read_at_serves_cold_docs_without_hydrating(self, tmp_path):
        """Time travel is a READ: a cold doc's whole history serves
        from its cold tick index + summaries — the pool never churns."""
        service, storm, hist, res = _stack(tmp_path, residency=True)
        _serve(service, storm, ["d0"], rounds=4)
        ref = _naive_prefixes(service, "d0")
        head = hist.head_seq("d0")
        res.evict("d0")
        assert not res.is_resident("d0")
        hydrations_before = res.stats["hydrations"]
        for s in (1, head // 2, head):
            assert hist.read_at("d0", s)["entries"] == ref[s]
        assert not res.is_resident("d0")  # reads never hydrate
        assert res.stats["hydrations"] == hydrations_before
        _close(storm)

    def test_read_beyond_head_and_below_floor(self, tmp_path):
        service, storm, hist, _ = _stack(
            tmp_path, tail_retention_summaries=0)
        _serve(service, storm, ["d0"], rounds=4)
        head = hist.head_seq("d0")
        with pytest.raises(HistoryError):
            hist.read_at("d0", head + 1)  # beyond head fails fast
        storm.checkpoint()
        assert hist.compact("d0") is not None
        assert hist.tail_floor("d0") == head
        # Exact summary state stays addressable; interior seqs are gone.
        assert hist.read_at("d0", head)["entries"]
        with pytest.raises(HistoryError):
            hist.read_at("d0", head - 1)
        _close(storm)


class TestCompaction:
    def test_compacted_reads_match_never_compacted_twin(self, tmp_path):
        """Summaries move read COST, never bytes: every seq still
        addressable after compaction reads byte-identical to the
        never-compacted twin."""
        s1, st1, h1, _ = _stack(tmp_path / "a",
                                tail_retention_summaries=1)
        s2, st2, h2, _ = _stack(tmp_path / "b")
        _serve(s1, st1, ["d0"], rounds=6)
        _serve(s2, st2, ["d0"], rounds=6)
        st1.checkpoint()
        mid_handle = h1.compact("d0")
        assert mid_handle is not None
        # Serve more, compact again — the chain grows, floor advances.
        for r in range(6, 9):
            for st, svc in ((st1, s1), (st2, s2)):
                client = "client-1"
                st.submit_frame(
                    None, {"rid": r,
                           "docs": [["d0", client, 1 + r * K, 1, K]]},
                    memoryview(_words(7, r, 0).tobytes()))
                st.flush()
        st1.checkpoint()
        assert h1.compact("d0") is not None
        h1.trim_now()
        floor = h1.tail_floor("d0")
        assert floor > 0
        head = h1.head_seq("d0")
        assert head == h2.head_seq("d0")
        for s in range(floor, head + 1):
            assert h1.read_at("d0", s) == h2.read_at("d0", s), s
        # The chain's exact states below the floor stay addressable too.
        chain_seq = h1.summary_seq("d0")
        assert h1.read_at("d0", chain_seq) == h2.read_at("d0", chain_seq)
        _close(st1)
        _close(st2)

    def test_trim_shrinks_spill_and_survives_restart(self, tmp_path):
        """The disk story: tail trim rewrites superseded tick blobs to
        fillers under the checkpoint watermark — the spill file
        genuinely shrinks and a restarted controller recovers
        byte-identically over the trimmed WAL."""
        service, storm, hist, _ = _stack(
            tmp_path, tail_retention_summaries=0, trim_batch_ticks=1)
        _serve(service, storm, ["d0", "d1"], rounds=6)
        storm.checkpoint()
        spill = tmp_path / "spill" / "storm_tick_words.log"
        before = os.path.getsize(spill)
        assert hist.compact("d0") and hist.compact("d1")
        assert hist.trim_now() == 0  # queued at compact time already
        assert hist.stats["trimmed_ticks"] > 0
        after = os.path.getsize(spill)
        assert after < before, (before, after)
        live = {d: storm.merge_host.map_entries(d, storm.datastore,
                                                storm.channel)
                for d in ("d0", "d1")}
        live_reads = {d: hist.read_at(d, hist.head_seq(d))
                      for d in ("d0", "d1")}
        _close(storm)
        service2, storm2, hist2, _ = _stack(tmp_path)
        storm2.recover()
        for d in ("d0", "d1"):
            assert storm2.merge_host.map_entries(
                d, storm2.datastore, storm2.channel) == live[d]
            assert hist2.read_at(d, hist2.head_seq(d)) == live_reads[d]
        _close(storm2)

    def test_maybe_compact_cadence_rolls_long_tails(self, tmp_path):
        """The background summarizer: tails past the op threshold roll
        on the flush maintenance cadence without explicit calls."""
        service, storm, hist, _ = _stack(
            tmp_path, summary_interval_ops=2 * K, compact_check_every=1)
        _serve(service, storm, ["d0"], rounds=6)
        assert hist.stats["compactions"] >= 1
        assert hist.summary_seq("d0") > 0
        # Reads above the newest summary fold only the short tail.
        head = hist.head_seq("d0")
        assert hist.read_at("d0", head)["entries"] == \
            storm.merge_host.map_entries("d0", storm.datastore,
                                         storm.channel)
        _close(storm)

    def test_quarantined_read_path_survives_trim(self, tmp_path):
        """quarantined_map_entries falls back to the summary fold once
        the record prefix is trimmed (the scalar-shadow seam)."""
        service, storm, hist, _ = _stack(
            tmp_path, tail_retention_summaries=0, trim_batch_ticks=1)
        _serve(service, storm, ["d0"], rounds=4)
        storm.checkpoint()
        assert hist.compact("d0")
        expect = storm.merge_host.map_entries("d0", storm.datastore,
                                              storm.channel)
        assert storm.quarantined_map_entries("d0") == expect
        _close(storm)


class TestBranches:
    def test_fork_seeds_byte_identical_planes(self, tmp_path):
        """fork at N ≡ replay-to-N, byte-for-byte: the branch's device
        planes equal the parent's planes captured right after seq N."""
        service, storm, hist, _ = _stack(tmp_path, spill=False)
        clients = _serve(service, storm, ["d0"], rounds=3,
                         checkpoint_first=False)
        xs = storm.merge_host._xstate
        prow = storm._storm_mrow("d0").row
        at_n = {f: np.asarray(getattr(xs, f)[prow])
                for f in ("present", "value", "vseq", "cleared_seq")}
        seq_n = storm.seq_host.checkpoint("d0").sequence_number
        for r in range(3, 6):  # the head moves past N
            storm.submit_frame(
                None, {"rid": r,
                       "docs": [["d0", clients["d0"], 1 + r * K, 1, K]]},
                memoryview(_words(7, r, 0).tobytes()))
            storm.flush()
        branch = hist.fork("d0", seq_n, name="b0")
        xs = storm.merge_host._xstate
        brow = storm._storm_mrow(branch).row
        for f in ("present", "value", "vseq", "cleared_seq"):
            np.testing.assert_array_equal(
                np.asarray(getattr(xs, f)[brow]), at_n[f], err_msg=f)
        cp = storm.seq_host.checkpoint(branch)
        assert cp.sequence_number == seq_n
        assert hist.read_at(branch, seq_n)["entries"] == \
            hist.read_at("d0", seq_n)["entries"]

    def test_branch_reads_below_fork_delegate_to_parent(self, tmp_path):
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=4)
        ref = _naive_prefixes(service, "d0")
        branch = hist.fork("d0", 17, name="b0")
        for s in (1, 9, 17):
            assert hist.read_at(branch, s)["entries"] == ref[s]
        meta = hist.branch_info(branch)
        assert meta == {"parent": "d0", "seq": 17, "name": "b0"}
        _close(storm)

    def test_branch_is_full_residency_citizen(self, tmp_path):
        """Cold-seeded branch: not resident at fork, hydrates through
        the normal admission path on first connect, serves, evicts."""
        service, storm, hist, res = _stack(tmp_path, residency=True)
        _serve(service, storm, ["d0"], rounds=3)
        branch = hist.fork("d0", 13, name="b0")
        assert not res.is_resident(branch)
        seed = hist.read_at(branch, 13)["entries"]
        assert not res.is_resident(branch)
        client = service.connect(branch, lambda m: None).client_id
        service.pump()
        assert res.is_resident(branch)
        assert storm.merge_host.map_entries(
            branch, storm.datastore, storm.channel) == seed
        storm.submit_frame(
            None, {"rid": "bw", "docs": [[branch, client, 1, 14, K]]},
            memoryview(_words(11, 0, 0).tobytes()))
        storm.flush()
        head = hist.head_seq(branch)
        assert head > 14
        assert hist.read_at(branch, head)["entries"] == \
            storm.merge_host.map_entries(branch, storm.datastore,
                                         storm.channel)
        # Eviction re-exports the branch's own cold record; reads keep
        # serving and rehydration converges.
        res.evict(branch)
        assert hist.read_at(branch, head)["entries"]
        _close(storm)

    def test_fork_control_replays_identically(self, tmp_path):
        """Recovery over the fork's WAL control re-seeds the branch
        (seeded writer included) byte-identically — including the
        branch's own post-fork serving ticks."""
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=4)
        branch = hist.fork("d0", 17, name="b0", writer="w0")
        storm.submit_frame(
            None, {"rid": "bw", "docs": [[branch, "w0", 1, 17, K]]},
            memoryview(_words(11, 0, 0).tobytes()))
        storm.flush()
        live_map = storm.merge_host.map_entries(branch, storm.datastore,
                                                storm.channel)
        live_cp = dataclasses.asdict(storm.seq_host.checkpoint(branch))
        _close(storm)
        service2, storm2, hist2, _ = _stack(tmp_path)
        storm2.recover()
        assert hist2.branch_info(branch) == {"parent": "d0", "seq": 17,
                                             "name": "b0"}
        assert storm2.merge_host.map_entries(
            branch, storm2.datastore, storm2.channel) == live_map
        rec_cp = dataclasses.asdict(storm2.seq_host.checkpoint(branch))
        for c in live_cp["clients"] + rec_cp["clients"]:
            c["last_update"] = 0  # arrival clock, not replica state
        assert rec_cp == live_cp
        _close(storm2)

    def test_fork_rejects_colliding_and_out_of_range(self, tmp_path):
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=2)
        hist.fork("d0", 9, name="b0")
        with pytest.raises(ValueError):
            hist.fork("d0", 9, name="b0")  # branch id taken
        with pytest.raises(ValueError):
            hist.fork("d0", 5, name="d0")  # self-fork
        with pytest.raises(HistoryError):
            hist.fork("d0", 10**6, name="b1")  # beyond head
        _close(storm)


class TestMergeBack:
    def _scenario(self, root):
        """Fork, write to branch AND parent concurrently, merge back.
        Returns (parent map, parent history cseq pairs, merge report)."""
        service, storm, hist, _ = _stack(root)
        clients = _serve(service, storm, ["d0"], rounds=3)
        branch = hist.fork("d0", 1 + 3 * K, name="b0", writer="w0")
        for r in range(3, 5):  # concurrent head writes + branch writes
            storm.submit_frame(
                None, {"rid": r,
                       "docs": [["d0", clients["d0"], 1 + r * K, 1, K]]},
                memoryview(_words(7, r, 0).tobytes()))
            rb = r - 3
            storm.submit_frame(
                None, {"rid": ("b", r),
                       "docs": [[branch, "w0", 1 + rb * K,
                                 1 + 3 * K, K]]},
                memoryview(_words(19, r, 0).tobytes()))
            storm.flush()
        report = hist.merge_back(branch)
        final = storm.merge_host.map_entries("d0", storm.datastore,
                                             storm.channel)
        head = hist.head_seq("d0")
        at_head = hist.read_at("d0", head)
        _close(storm)
        return final, at_head, report

    def test_merge_back_resequences_through_ordinary_path(self,
                                                          tmp_path):
        final, at_head, report = self._scenario(tmp_path / "run")
        assert report["merged_ops"] == 2 * K
        assert at_head["entries"] == final

    def test_merge_back_deterministic_under_concurrent_writes(
            self, tmp_path):
        """Two identical runs (fork + concurrent parent/branch writes +
        merge-back) converge byte-identically — ordinary sequencing IS
        the merge machinery."""
        a = self._scenario(tmp_path / "a")
        b = self._scenario(tmp_path / "b")
        assert a == b

    def test_merge_back_of_unwritten_branch_is_noop(self, tmp_path):
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=2)
        branch = hist.fork("d0", 9, name="b0")
        before = storm.seq_host.checkpoint("d0").sequence_number
        report = hist.merge_back(branch)
        assert report["merged_ops"] == 0
        assert storm.seq_host.checkpoint("d0").sequence_number == before
        _close(storm)


class TestServiceSurface:
    def test_routerlicious_and_driver_surface(self, tmp_path):
        """read_at/fork/merge_back through the service facade + the
        client-side HistoricalDocumentService (in-process transport)."""
        from fluidframework_tpu.drivers.history_driver import (
            HistoricalDocumentService,
        )
        service, storm, hist, _ = _stack(tmp_path)
        _serve(service, storm, ["d0"], rounds=3)
        ref = _naive_prefixes(service, "d0")
        svc = HistoricalDocumentService(service, "d0", seq=9)
        assert svc.entries() == ref[9]
        assert svc.read_at(5)["entries"] == ref[5]
        deltas = svc.get_deltas(0)
        assert max(m.sequence_number for m in deltas) <= 9
        br = svc.fork(name="b0")
        assert hist.is_branch(br.doc_id)
        assert br.entries() == ref[9]
        with pytest.raises(TypeError):
            br.connect(lambda m: None)
        assert br.merge_back()["merged_ops"] == 0
        _close(storm)

    def test_history_plane_requires_snapshots(self):
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=4)
        merge_host = KernelMergeHost(flush_threshold=10**9)
        service = RouterliciousService(merge_host=merge_host,
                                       batched_deli_host=seq_host,
                                       auto_pump=False,
                                       idle_check_interval=10**9)
        storm = StormController(service, seq_host, merge_host,
                                flush_threshold_docs=10**9)
        with pytest.raises(ValueError):
            HistoryPlane(storm, snapshots=None)


def test_render_history_line():
    from fluidframework_tpu.tools.monitor import render_history
    assert render_history({}) == ""
    line = render_history({
        "history.branches": 2, "history.compactions": 5,
        "history.trimmed_ticks": 12, "history.tail_ops": 96,
        "history.reads": 30, "history.read_s.p99": 0.0012,
        "history.merges": 1,
    })
    assert "branches 2" in line and "trimmed-ticks 12" in line
    assert "tail 96 ops" in line and "merges 1" in line
    windowed = render_history(
        {"history.branches": 2, "history.compactions": 9,
         "history.reads": 50},
        prev={"history.compactions": 5, "history.reads": 30},
        interval=2.0)
    assert "compactions 2.00/s" in windowed


class TestReanchorAndPins:
    """Round 19 satellites: summary-chain re-anchoring (ROADMAP 5c —
    head records stay O(depth) while anchored exact states remain
    addressable) and paid-tier retention pins (ROADMAP 5d — riddler's
    tier column gates who may hold history against the trim)."""

    def test_chain_reanchors_past_depth_cap(self, tmp_path):
        """Ten compactions against a depth-4 cap: the inline chain
        stays bounded, the overflow rolls into linked anchor pages,
        and EVERY prior summary's exact state still reads
        byte-identical to a never-compacted twin through the page
        walk."""
        s, st, h, _ = _stack(tmp_path / "a", chain_reanchor_depth=4)
        s2, st2, h2, _ = _stack(tmp_path / "b")  # never-compacted twin
        _serve(s, st, ["d0"], rounds=1)
        _serve(s2, st2, ["d0"], rounds=1)
        assert h.compact("d0")
        summary_seqs = [h.summary_seq("d0")]
        for r in range(1, 10):
            for stx in (st, st2):
                stx.submit_frame(
                    None, {"rid": r,
                           "docs": [["d0", "client-1", 1 + r * K, 1, K]]},
                    memoryview(_words(7, r, 0).tobytes()))
                stx.flush()
            assert h.compact("d0")
            summary_seqs.append(h.summary_seq("d0"))
        rec = h._summary_record("d0")
        assert len(rec["chain"]) <= 4  # bounded inline
        assert rec["anchor"]["handle"]
        assert h.stats["reanchors"] >= 2  # pages form a linked list
        for sq in summary_seqs:
            assert h.read_at("d0", sq) == h2.read_at("d0", sq), sq
        _close(st)
        _close(st2)

    def test_reanchor_disabled_keeps_unbounded_chain(self, tmp_path):
        s, st, h, _ = _stack(tmp_path, chain_reanchor_depth=None)
        _serve(s, st, ["d0"], rounds=1)
        for r in range(6):
            if r:
                st.submit_frame(
                    None, {"rid": r,
                           "docs": [["d0", "client-1", 1 + r * K, 1, K]]},
                    memoryview(_words(7, r, 0).tobytes()))
                st.flush()
            assert h.compact("d0")
        rec = h._summary_record("d0")
        assert len(rec["chain"]) == 5 and "anchor" not in rec
        assert h.stats["reanchors"] == 0
        _close(st)

    def test_pin_blocks_trim_then_unpin_releases(self, tmp_path):
        """A pinned range clamps the trim floor (reads inside it stay
        exact while unpinned history trims away); dropping the pin
        lets the next compaction cadence reclaim what it held."""
        s, st, h, _ = _stack(tmp_path / "a",
                             tail_retention_summaries=0,
                             trim_batch_ticks=10**9)
        s2, st2, h2, _ = _stack(tmp_path / "b")
        _serve(s, st, ["d0"], rounds=4)
        _serve(s2, st2, ["d0"], rounds=4)
        st.checkpoint()
        h.pin_range("tenant-a", "d0", 5, 20)
        assert h.compact("d0")
        h.trim_now()
        assert h.tail_floor("d0") <= 5  # clamped by the pin
        for sq in (5, 12, 20):
            assert h.read_at("d0", sq) == h2.read_at("d0", sq), sq
        trimmed_before = h.stats["trimmed_ticks"]
        assert h.unpin_range("tenant-a", "d0")
        assert not h.unpin_range("tenant-a", "d0")  # idempotent
        for r in (4, 5):
            for stx in (st, st2):
                stx.submit_frame(
                    None, {"rid": r,
                           "docs": [["d0", "client-1", 1 + r * K, 1, K]]},
                    memoryview(_words(7, r, 0).tobytes()))
                stx.flush()
        st.checkpoint()
        assert h.compact("d0")
        h.trim_now()
        assert h.stats["trimmed_ticks"] > trimmed_before
        assert h.tail_floor("d0") > 5  # the pin's hold is gone
        _close(st)
        _close(st2)

    def test_pins_gated_on_riddler_paid_tier(self, tmp_path):
        from fluidframework_tpu.server.riddler import TenantManager
        tm = TenantManager()
        tm.create_tenant("pro-t", tier="pro")
        tm.create_tenant("free-t", tier="free")
        tm.create_tenant("std-t", tier="standard")
        s, st, h, _ = _stack(tmp_path, tenant_source=tm)
        _serve(s, st, ["d0"], rounds=2)
        for t in ("free-t", "std-t", "no-such-tenant"):
            with pytest.raises(HistoryError):
                h.pin_range(t, "d0", 1, 8)
        assert h.stats["pins"] == 0
        pin = h.pin_range("pro-t", "d0", 1, 8)
        assert pin == {"tenant": "pro-t", "doc": "d0", "lo": 1, "hi": 8}
        assert h.stats["pins"] == 1
        with pytest.raises(ValueError):
            h.pin_range("pro-t", "d0", 9, 2)  # inverted range
        _close(st)

    def test_pins_replay_through_recovery(self, tmp_path):
        """Pins are journaled "hp" controls: a recovered plane holds
        exactly the pins that were live — an unpinned pin stays
        gone."""
        s, st, h, _ = _stack(tmp_path)
        _serve(s, st, ["d0"], rounds=2)
        h.pin_range("tenant-a", "d0", 3, 9)
        h.pin_range("tenant-b", "d0", 1, 4)
        h.unpin_range("tenant-b", "d0")
        _close(st)
        s2, st2, h2, _ = _stack(tmp_path)
        st2.recover()
        assert h2.pins == {("tenant-a", "d0"): (3, 9)}
        _close(st2)
