"""Debugger driver + service monitor (packages/drivers/debugger,
server/service-monitor analogs)."""

from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

import pytest

from fluidframework_tpu.tools.debug_tool import load_session
from fluidframework_tpu.tools.monitor import scrape
from fluidframework_tpu.tools.replay import canonical, replay_summary

GOLDENS = Path(__file__).parent / "goldens"


class TestDebuggerDriver:
    @pytest.mark.parametrize("name", ["string-conflict", "map-directory"])
    def test_step_through_matches_truncated_replays(self, name):
        directory = GOLDENS / name
        service, container = load_session(directory)
        assert service.cursor == 0

        # Step in uneven increments; at every stop the container must equal
        # a fresh truncated replay at that cursor (replayTo parity).
        stops = []
        while service.cursor < service.end_seq:
            batch = service.step(5)
            if not batch:
                break
            stops.append(service.cursor)
        assert stops, "no ops recorded"
        assert service.cursor == service.end_seq

        final = canonical(container.summarize())
        assert final == canonical(replay_summary(directory))

        mid = stops[len(stops) // 2]
        svc2, container2 = load_session(directory)
        svc2.play_to(mid)
        assert canonical(container2.summarize()) == canonical(
            replay_summary(directory, up_to_seq=mid))

    def test_cursor_clamps_delta_storage(self, tmp_path):
        directory = GOLDENS / "string-conflict"
        service, _container = load_session(directory)
        service.step(3)
        fetched = service.delta_storage.get_deltas(0)
        assert all(m.sequence_number <= service.cursor for m in fetched)

    def test_play_is_idempotent_at_end(self):
        service, container = load_session(GOLDENS / "string-conflict")
        service.play()
        assert service.play() == []
        assert service.step() == []


class TestServiceMonitor:
    def test_scrape_live_service_metrics(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])

            metrics = scrape("127.0.0.1", port)
            assert isinstance(metrics, dict)

            # Drive one real client round trip, then the scrape must show
            # front-door and sequencing activity.
            from fluidframework_tpu.dds.map import SharedMap
            from fluidframework_tpu.drivers.tinylicious_driver import (
                TinyliciousDocumentServiceFactory,
            )
            from fluidframework_tpu.runtime.container import Container
            factory = TinyliciousDocumentServiceFactory(port=port)
            svc = factory("doc")
            container = Container.create_detached(svc)
            ds = container.runtime.create_datastore("default")
            ds.create_channel("root", SharedMap.channel_type)
            with svc.dispatch_lock:
                container.attach()
                ds.get_channel("root").set("k", 1)
            deadline = time.monotonic() + 30
            while (container.runtime.pending.has_pending
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert not container.runtime.pending.has_pending
            svc.close()

            after = scrape("127.0.0.1", port)
            assert after.get("alfred.connects", 0) >= 1
            assert after.get("deli.sequenced_ops", 0) >= 1
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_monitor_cli_once(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY ")
            port = int(line.split()[1])
            out = subprocess.run(
                [sys.executable, "-m", "fluidframework_tpu.tools.monitor",
                 "--port", str(port), "--once"],
                capture_output=True, text=True, timeout=60)
            assert out.returncode == 0, out.stderr
            import json
            assert isinstance(json.loads(out.stdout), dict)
        finally:
            proc.terminate()
            proc.wait(timeout=10)
