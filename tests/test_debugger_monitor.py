"""Debugger driver + service monitor (packages/drivers/debugger,
server/service-monitor analogs)."""

from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

import pytest

from fluidframework_tpu.tools.debug_tool import load_session
from fluidframework_tpu.tools.monitor import scrape
from fluidframework_tpu.tools.replay import canonical, replay_summary

GOLDENS = Path(__file__).parent / "goldens"


class TestDebuggerDriver:
    @pytest.mark.parametrize("name", ["string-conflict", "map-directory"])
    def test_step_through_matches_truncated_replays(self, name):
        directory = GOLDENS / name
        service, container = load_session(directory)
        assert service.cursor == 0

        # Step in uneven increments; at every stop the container must equal
        # a fresh truncated replay at that cursor (replayTo parity).
        stops = []
        while service.cursor < service.end_seq:
            batch = service.step(5)
            if not batch:
                break
            stops.append(service.cursor)
        assert stops, "no ops recorded"
        assert service.cursor == service.end_seq

        final = canonical(container.summarize())
        assert final == canonical(replay_summary(directory))

        mid = stops[len(stops) // 2]
        svc2, container2 = load_session(directory)
        svc2.play_to(mid)
        assert canonical(container2.summarize()) == canonical(
            replay_summary(directory, up_to_seq=mid))

    def test_cursor_clamps_delta_storage(self, tmp_path):
        directory = GOLDENS / "string-conflict"
        service, _container = load_session(directory)
        service.step(3)
        fetched = service.delta_storage.get_deltas(0)
        assert all(m.sequence_number <= service.cursor for m in fetched)

    def test_play_is_idempotent_at_end(self):
        service, container = load_session(GOLDENS / "string-conflict")
        service.play()
        assert service.play() == []
        assert service.step() == []


class TestServiceMonitor:
    def test_scrape_live_service_metrics(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])

            metrics = scrape("127.0.0.1", port)
            assert isinstance(metrics, dict)

            # Drive one real client round trip, then the scrape must show
            # front-door and sequencing activity.
            from fluidframework_tpu.dds.map import SharedMap
            from fluidframework_tpu.drivers.tinylicious_driver import (
                TinyliciousDocumentServiceFactory,
            )
            from fluidframework_tpu.runtime.container import Container
            factory = TinyliciousDocumentServiceFactory(port=port)
            svc = factory("doc")
            container = Container.create_detached(svc)
            ds = container.runtime.create_datastore("default")
            ds.create_channel("root", SharedMap.channel_type)
            with svc.dispatch_lock:
                container.attach()
                ds.get_channel("root").set("k", 1)
            deadline = time.monotonic() + 30
            while (container.runtime.pending.has_pending
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert not container.runtime.pending.has_pending
            svc.close()

            after = scrape("127.0.0.1", port)
            assert after.get("alfred.connects", 0) >= 1
            assert after.get("deli.sequenced_ops", 0) >= 1
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_watch_json_mode_emits_lines_with_deltas(self, monkeypatch):
        import io
        import json

        from fluidframework_tpu.tools import monitor
        scrapes = iter([{"deli.sequenced_ops": 10.0},
                        {"deli.sequenced_ops": 25.0}])
        monkeypatch.setattr(monitor, "scrape",
                            lambda *a, **k: next(scrapes))
        out = io.StringIO()
        monitor.watch("h", 1, interval=0.0, out=out, as_json=True,
                      max_polls=2)
        lines = [json.loads(line) for line in
                 out.getvalue().strip().splitlines()]
        assert lines[0]["deli.sequenced_ops"] == 10.0
        assert "+deli.sequenced_ops" not in lines[0]
        assert lines[1]["+deli.sequenced_ops"] == 15.0

    def test_watch_reconnects_after_restart(self, monkeypatch):
        """A restarting service must not kill the watcher: the failed
        scrape reports and the next interval picks the service back up —
        in BOTH output modes."""
        import io
        import json

        from fluidframework_tpu.tools import monitor
        for as_json in (True, False):
            calls = {"n": 0}

            def scrape(*a, **k):
                calls["n"] += 1
                if calls["n"] == 2:  # the restart window
                    raise ConnectionError("refused")
                return {"alfred.connects": float(calls["n"])}

            monkeypatch.setattr(monitor, "scrape", scrape)
            out = io.StringIO()
            monitor.watch("h", 1, interval=0.0, out=out, as_json=as_json,
                          max_polls=3)
            text = out.getvalue()
            assert calls["n"] == 3  # kept polling through the outage
            if as_json:
                lines = [json.loads(line)
                         for line in text.strip().splitlines()]
                assert "unreachable" in lines[1]
                assert lines[2]["alfred.connects"] == 3.0
            else:
                assert "unreachable" in text
                assert "alfred.connects" in text

    def test_stage_bar_renders_attribution(self):
        from fluidframework_tpu.tools.monitor import (
            render_stage_bar, stage_shares)
        metrics = {}
        for stage, mean in (("device_dispatch", 0.006),
                            ("readback", 0.003),
                            ("wal_commit_wait", 0.001)):
            metrics[f"storm.stage.{stage}.mean"] = mean
            metrics[f"storm.stage.{stage}.count"] = 100.0
            metrics[f"storm.stage.{stage}.p50"] = mean
            metrics[f"storm.stage.{stage}.p99"] = mean * 2
        shares = stage_shares(metrics)
        assert abs(shares["device_dispatch"] - 0.6) < 1e-9
        assert abs(sum(shares.values()) - 1.0) < 1e-9
        text = render_stage_bar(metrics)
        assert "device_dispatch" in text and "60.0%" in text
        assert "p99" in text
        # No ticks yet: the bar degrades, never divides by zero.
        assert "no storm ticks" in render_stage_bar({})
        # Windowed shares: vs a prev snapshot, only the NEW attributed
        # time counts — a behavior shift shows immediately however long
        # the cumulative history is.
        later = dict(metrics)
        later["storm.stage.wal_commit_wait.mean"] = 0.1
        later["storm.stage.wal_commit_wait.count"] = 101.0
        windowed = stage_shares(later, prev=metrics)
        assert windowed["wal_commit_wait"] > 0.9  # the stall dominates
        assert stage_shares(later)["wal_commit_wait"] < 0.92  # cumulative
        # An idle window (no new ticks) falls back to cumulative.
        assert stage_shares(metrics, prev=metrics) == stage_shares(metrics)
        # A service RESTART resets the registry: mixed-sign windows must
        # fall back to the new cumulative totals, never render shares
        # outside [0, 1].
        post = {"storm.stage.device_dispatch.mean": 0.001,
                "storm.stage.device_dispatch.count": 10.0,
                "storm.stage.wal_commit_wait.mean": 0.1,
                "storm.stage.wal_commit_wait.count": 20.0}
        shares = stage_shares(post, prev=metrics)  # prev from old process
        assert shares == stage_shares(post)
        assert all(0.0 <= v <= 1.0 for v in shares.values())

    def test_monitor_cli_once(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY ")
            port = int(line.split()[1])
            out = subprocess.run(
                [sys.executable, "-m", "fluidframework_tpu.tools.monitor",
                 "--port", str(port), "--once"],
                capture_output=True, text=True, timeout=60)
            assert out.returncode == 0, out.stderr
            import json
            assert isinstance(json.loads(out.stdout), dict)
        finally:
            proc.terminate()
            proc.wait(timeout=10)


def test_rebalance_line_renders_fire_rate():
    """Round-11 rebalance attribution line: silent until anything fires,
    fires-per-tick over the poll window (stage-ledger scatter count is
    the tick denominator), cumulative fallback across restarts."""
    from fluidframework_tpu.tools.monitor import render_rebalance

    assert render_rebalance({}) == ""  # nothing ever fired → no line
    m = {"storm.device.rebalance_fired": 4.0,
         "storm.device.blocks_touched": 36.0,
         "storm.stage.scatter.count": 16.0,
         "merge.rebalance_fires": 2.0,
         "merge.geometry_retunes": 1.0}
    text = render_rebalance(m)
    assert "0.25/tick" in text
    assert "blocks_touched 36" in text
    assert "retunes 1" in text
    # Windowed: only the poll window's fires/ticks/touched count —
    # (4-2)/(16-8) fires per tick, 36-30 blocks touched.
    prev = {"storm.device.rebalance_fired": 2.0,
            "storm.stage.scatter.count": 8.0,
            "storm.device.blocks_touched": 30.0}
    windowed = render_rebalance(m, prev)
    assert "0.25/tick" in windowed
    assert "blocks_touched 6" in windowed
    # A service restart resets the registry (negative window): fall back
    # to the new cumulative totals rather than rendering garbage.
    prev_big = {"storm.device.rebalance_fired": 10.0,
                "storm.stage.scatter.count": 100.0}
    assert "0.25/tick" in render_rebalance(m, prev_big)


def test_pipeline_line_renders_depth_and_overlap():
    """Round-14 pipeline line: silent until a tick records a wall split,
    then configured depth + wall vs attributed stage time + the overlap
    share (the fsync/dispatch concurrency), windowed against the
    previous poll with the cumulative fallback across restarts — and
    the raw metrics (storm.pipeline.depth, storm.stage.wall.*) flow
    through --json untouched."""
    import io
    import json

    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_pipeline

    assert render_pipeline({}) == ""  # no wall splits ever → no line
    # 10 ticks: wall 1.0s each, dispatch 0.8s + commit-wait 0.6s each →
    # attributed 14s over 10s of wall = 4s overlap (40% of wall).
    m = {"storm.pipeline.depth": 1.0,
         "storm.stage.wall.mean": 1.0, "storm.stage.wall.count": 10.0,
         "storm.stage.device_dispatch.mean": 0.8,
         "storm.stage.device_dispatch.count": 10.0,
         "storm.stage.wal_commit_wait.mean": 0.6,
         "storm.stage.wal_commit_wait.count": 10.0}
    text = render_pipeline(m)
    assert "depth 1" in text
    assert "wall 10,000ms" in text
    assert "overlap 4,000ms" in text and "(40% of wall)" in text
    # Windowed: only the poll window's 5 ticks count — wall 5s,
    # attributed 7s, overlap 2s.
    prev = {"storm.stage.wall.mean": 1.0, "storm.stage.wall.count": 5.0,
            "storm.stage.device_dispatch.mean": 0.8,
            "storm.stage.device_dispatch.count": 5.0,
            "storm.stage.wal_commit_wait.mean": 0.6,
            "storm.stage.wal_commit_wait.count": 5.0}
    windowed = render_pipeline(m, prev)
    assert "wall 5,000ms" in windowed
    assert "overlap 2,000ms" in windowed and "ticks 5" in windowed
    # Restart (negative window): fall back to cumulative totals.
    prev_big = {"storm.stage.wall.mean": 1.0,
                "storm.stage.wall.count": 99.0}
    assert "wall 10,000ms" in render_pipeline(m, prev_big)
    # Human watch mode carries the line; --json mode passes the raw
    # snapshot through, so the new metrics ride it untouched.
    human = monitor.render_human(m, prev, interval=2.0)
    assert "pipeline: depth 1" in human
    scrapes = iter([dict(m)])
    out = io.StringIO()

    def fake_scrape(host, port, timeout=10.0):
        return next(scrapes)

    real_scrape, monitor.scrape = monitor.scrape, fake_scrape
    try:
        monitor.watch("h", 1, interval=0.0, out=out, as_json=True,
                      max_polls=1)
    finally:
        monitor.scrape = real_scrape
    line = json.loads(out.getvalue().splitlines()[0])
    assert line["storm.pipeline.depth"] == 1.0
    assert line["storm.stage.wall.count"] == 10.0


def test_viewer_line_renders_broadcast_plane():
    """Round-13 viewer-plane line: silent until a viewer ever joins,
    gauge levels + windowed broadcast-bytes and lag-drop rates, the
    serialize-once evidence column, cumulative fallback across
    restarts — and the raw metrics flow through --json untouched."""
    import io
    import json

    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_viewers

    assert render_viewers({}) == ""  # no viewer plane → no line
    m = {"viewer.rooms": 2.0,
         "viewer.viewers": 100000.0,
         "viewer.broadcast_bytes": 4096.0,
         "viewer.lag_drops": 10.0,
         "viewer.tick_encodes": 20.0,
         "viewer.delivered_frames": 2000000.0}
    text = render_viewers(m)
    assert "rooms 2" in text and "viewers 100000" in text
    assert "encodes 20 / frames 2,000,000" in text
    # Windowed rates over a 2s poll: (4096-2048)/2 and (10-6)/2.
    prev = {"viewer.broadcast_bytes": 2048.0, "viewer.lag_drops": 6.0}
    windowed = render_viewers(m, prev, interval=2.0)
    assert "1,024B/s" in windowed
    assert "lag-drops 2.0/s" in windowed
    # Restart (negative window): fall back to cumulative counts.
    prev_big = {"viewer.broadcast_bytes": 99999.0, "viewer.lag_drops": 0.0}
    assert "4,096B/s" in render_viewers(m, prev_big, interval=1.0)
    # Human watch mode carries the line; --json carries raw metrics.
    human = monitor.render_human(m, prev, interval=2.0)
    assert "viewers: rooms 2" in human

    scrapes = iter([dict(m)])
    real_scrape = monitor.scrape
    monitor.scrape = lambda *a, **k: next(scrapes)
    try:
        out = io.StringIO()
        monitor.watch("h", 1, interval=0.0, out=out, as_json=True,
                      max_polls=1)
    finally:
        monitor.scrape = real_scrape
    line = json.loads(out.getvalue().strip())
    assert line["viewer.viewers"] == 100000.0
    assert line["viewer.tick_encodes"] == 20.0


def test_residency_line_renders_tiering_state():
    """Round-12 residency line: silent without a residency manager,
    gauge levels + windowed hydration/eviction rates + hydration p99 +
    RSS, cumulative fallback across restarts — and the same metrics
    flow through --json watch mode untouched."""
    import io
    import json

    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_residency

    assert render_residency({}) == ""  # no manager attached → no line
    m = {"residency.hot_docs": 100.0,
         "residency.known_cold_docs": 9900.0,
         "residency.hydrating_docs": 3.0,
         "residency.hydrations": 50.0,
         "residency.evictions": 40.0,
         "residency.hydrate_s.p99": 0.0042,
         "residency.rss_mb": 512.0}
    text = render_residency(m)
    assert "hot 100" in text and "cold 9900" in text
    assert "hydrating 3" in text
    assert "4.200ms" in text
    assert "rss 512MB" in text
    # Windowed rates over a 2s poll: (50-40)/2 and (40-38)/2.
    prev = {"residency.hydrations": 40.0, "residency.evictions": 38.0}
    windowed = render_residency(m, prev, interval=2.0)
    assert "hydrations 5.0/s" in windowed
    assert "evictions 1.0/s" in windowed
    # Restart (negative window): fall back to cumulative counts.
    prev_big = {"residency.hydrations": 999.0, "residency.evictions": 0.0}
    assert "hydrations 50.0/s" in render_residency(m, prev_big,
                                                   interval=1.0)
    # Human watch mode carries the line; --json carries the raw metrics.
    human = monitor.render_human(m, prev, interval=2.0)
    assert "residency: hot 100" in human

    scrapes = iter([dict(m)])
    real_scrape = monitor.scrape
    monitor.scrape = lambda *a, **k: next(scrapes)
    try:
        out = io.StringIO()
        monitor.watch("h", 1, interval=0.0, out=out, as_json=True,
                      max_polls=1)
    finally:
        monitor.scrape = real_scrape
    line = json.loads(out.getvalue().strip())
    assert line["residency.hot_docs"] == 100.0
    assert line["residency.rss_mb"] == 512.0


def test_megadoc_line_renders_write_scaleout_plane():
    """Round-15 mega-doc line: silent until a doc is promoted, then
    promoted/lane gauge levels, lanes per doc, combiner occupancy, and
    windowed combined-op / boundary-exchange rates with the cumulative
    fallback across restarts — and the same metrics flow through --json
    watch mode untouched."""
    import io
    import json

    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_megadoc

    assert render_megadoc({}) == ""  # nothing ever promoted → no line
    m = {"megadoc.promoted_docs": 2.0,
         "megadoc.total_lanes": 8.0,
         "megadoc.combiner_occupancy": 0.75,
         "megadoc.combined_ops": 1000.0,
         "megadoc.boundary_exchanges": 640.0}
    text = render_megadoc(m)
    assert "promoted 2" in text
    assert "lanes 8 (4.0/doc)" in text
    assert "occupancy 0.75" in text
    # Windowed rates over a 2s poll window.
    prev = {"megadoc.combined_ops": 900.0,
            "megadoc.boundary_exchanges": 600.0}
    windowed = render_megadoc(m, prev, interval=2.0)
    assert "combined 50.0/s" in windowed
    assert "boundary-exchanges 20.0/s" in windowed
    # Restart (negative window): fall back to cumulative counts.
    prev_big = {"megadoc.combined_ops": 99999.0,
                "megadoc.boundary_exchanges": 0.0}
    assert "combined 1,000.0/s" in render_megadoc(m, prev_big,
                                                  interval=1.0)
    # Human watch mode carries the line; --json the raw metrics.
    human = monitor.render_human(m, prev, interval=2.0)
    assert "megadoc: promoted 2" in human

    scrapes = iter([dict(m)])
    real_scrape = monitor.scrape
    monitor.scrape = lambda *a, **k: next(scrapes)
    try:
        out = io.StringIO()
        monitor.watch("h", 1, interval=0.0, out=out, as_json=True,
                      max_polls=1)
    finally:
        monitor.scrape = real_scrape
    line = json.loads(out.getvalue().strip())
    assert line["megadoc.total_lanes"] == 8.0
    assert line["megadoc.combiner_occupancy"] == 0.75


def test_cluster_line_renders_placement_plane():
    """Round-16 cluster line: silent without a placement directory,
    then active hosts, docs on this host, migration count (windowed
    rate) + in-flight gauge, viewer re-homes and the last migration's
    blackout ms — and the line rides human watch mode."""
    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_cluster

    assert render_cluster({}) == ""  # no cluster directory → no line
    m = {"cluster.hosts": 4.0,
         "cluster.host_docs": 12.0,
         "cluster.migrations": 9.0,
         "cluster.migrations_in_flight": 1.0,
         "cluster.last_blackout_ms": 23.5,
         "viewer.rehomes": 3.0}
    text = render_cluster(m)
    assert "hosts 4" in text
    assert "docs/host 12" in text
    assert "migrations 9" in text
    assert "in-flight 1" in text
    assert "viewer re-homes 3" in text
    assert "last blackout 23.5ms" in text
    # Windowed migration rate over a 2s poll window.
    windowed = render_cluster(m, {"cluster.migrations": 5.0},
                              interval=2.0)
    assert "(2.00/s)" in windowed
    # Restart (negative window): cumulative count, no rate suffix.
    assert "(" not in render_cluster(m, {"cluster.migrations": 99.0},
                                     interval=1.0)
    human = monitor.render_human(m, {}, interval=1.0)
    assert "cluster: hosts 4" in human


def test_replication_line_renders_plane_state():
    """Round-19 replication line: silent without a replication plane,
    then role, follower count, lag, watermark gap, shipped batches
    (windowed rate) and the last failover's blackout ms — and the line
    rides human watch mode."""
    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_replication

    assert render_replication({}) == ""  # no plane → no line
    m = {"repl.role_code": 1.0,
         "repl.followers": 2.0,
         "repl.lag": 3.0,
         "repl.watermark_gap": 1.0,
         "repl.shipped_batches": 40.0,
         "repl.last_failover_blackout_ms": 712.135}
    text = render_replication(m)
    assert "role leader" in text
    assert "followers 2" in text
    assert "lag 3" in text
    assert "watermark-gap 1" in text
    assert "shipped 40" in text
    assert "last failover blackout 712.1ms" in text
    # A fenced ex-leader shows as demoted.
    assert "role demoted" in render_replication(
        dict(m, **{"repl.role_code": 3.0}))
    # Windowed ship rate over a 2s poll window.
    windowed = render_replication(m, {"repl.shipped_batches": 30.0},
                                  interval=2.0)
    assert "(5.0/s)" in windowed
    # Restart (negative window): cumulative count, no rate suffix.
    assert "(" not in render_replication(
        m, {"repl.shipped_batches": 99.0}, interval=1.0)
    human = monitor.render_human(m, {}, interval=1.0)
    assert "replication: role leader" in human


def test_transport_line_renders_wire_state():
    """Round-21 networked-transport line: silent when replication is
    purely in-process (no transport gauges), then link count, RTT
    p50/p99, windowed retransmit rate, heartbeat misses, open
    partitions, the parked-write depth and time-in-degraded-mode — and
    the line rides human watch mode."""
    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_transport

    assert render_transport({}) == ""  # in-process plane → no line
    m = {"transport.links": 2.0,
         "transport.rtt_p50_ms": 0.8,
         "transport.rtt_p99_ms": 4.25,
         "transport.retransmits": 12.0,
         "transport.heartbeat_misses": 3.0,
         "transport.open_partitions": 1.0,
         "repl.parked_docs": 5.0,
         "repl.degraded_s": 1.75}
    text = render_transport(m)
    assert "links 2" in text
    assert "rtt p50 0.8ms p99 4.2ms" in text
    assert "retransmits 12" in text
    assert "hb-misses 3" in text
    assert "open-partitions 1" in text
    assert "parked 5" in text
    assert "DEGRADED 1.8s" in text
    # A healthy quorum renders the ok state, not a degraded clock.
    healthy = render_transport(dict(m, **{"repl.degraded_s": 0.0,
                                          "transport.open_partitions": 0.0,
                                          "repl.parked_docs": 0.0}))
    assert "quorum ok" in healthy and "DEGRADED" not in healthy
    # Windowed retransmit rate over a 2s poll window; a restart
    # (negative window) falls back to the cumulative count.
    windowed = render_transport(m, {"transport.retransmits": 2.0},
                                interval=2.0)
    assert "retransmits 12 (5.0/s)" in windowed
    assert "(" not in render_transport(
        m, {"transport.retransmits": 99.0}, interval=1.0).split("rtt")[1]
    human = monitor.render_human(m, {}, interval=1.0)
    assert "transport: links 2" in human


def test_replicas_line_renders_read_tier_state():
    """Round-20 read-replica line: silent without a balancer scrape,
    then host/room counts, the per-room staleness distribution (the
    bound a replica-served read can be behind by), windowed re-home
    and redirect rates — and the line rides human watch mode."""
    from fluidframework_tpu.tools import monitor
    from fluidframework_tpu.tools.monitor import render_replicas

    assert render_replicas({}) == ""  # no balancer → no line
    m = {"replica.hosts": 2.0,
         "replica.rooms": 3.0,
         "replica.staleness_seqs.p50": 0.0,
         "replica.staleness_seqs.p99": 8.0,
         "replica.staleness_worst": 8.0,
         "replica.rehomed_viewers": 12.0,
         "replica.redirects": 5.0,
         "replica.stale_redirects": 1.0}
    text = render_replicas(m)
    assert "hosts 2" in text
    assert "rooms 3 (1.5/replica)" in text
    assert "staleness p50 0 p99 8 worst 8 seqs" in text
    assert "re-homed 12" in text
    assert "redirects 6" in text  # routing + stale sheds combined
    # Windowed rates over a 2s poll window.
    windowed = render_replicas(
        m, {"replica.rehomed_viewers": 2.0, "replica.redirects": 2.0,
            "replica.stale_redirects": 0.0}, interval=2.0)
    assert "re-homed 12 (5.0/s)" in windowed
    assert "redirects 6 (2.0/s)" in windowed
    human = monitor.render_human(m, {}, interval=1.0)
    assert "replicas: hosts 2" in human
