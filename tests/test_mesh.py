"""Mesh sharding tests: the document axis sharded over 8 virtual devices.

Validates the framework's multi-chip thesis (SURVEY.md §2.9: documents are
the data-parallel axis; the merge path needs no collectives) on the CPU
mesh that conftest.py provisions: every kernel runs sharded over 8 devices
with output shards resident on all of them, bit-identical to the unsharded
run, and metrics aggregate via the one psum collective.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops import map_kernel as mk
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import sequencer as seqk
from fluidframework_tpu.parallel import mesh as pmesh
from fluidframework_tpu.protocol.messages import MessageType

NUM_DOCS = 16  # 2 per device on the 8-device mesh


@pytest.fixture(scope="module")
def mesh(cpu_mesh_devices):
    return pmesh.make_mesh(cpu_mesh_devices[:8])


def _devices_holding(arr):
    return {shard.device for shard in arr.addressable_shards}


def _assert_match_and_sharded(sharded_out, plain_out, mesh):
    """Every leaf bit-identical to the unsharded run; leading-axis leaves
    resident on all mesh devices."""
    s_leaves = jax.tree_util.tree_leaves(sharded_out)
    p_leaves = jax.tree_util.tree_leaves(plain_out)
    assert len(s_leaves) == len(p_leaves)
    n_dev = mesh.devices.size
    for s, p in zip(s_leaves, p_leaves):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(p))
        assert len(_devices_holding(s)) == n_dev


def _seq_inputs():
    state = seqk.init_state(NUM_DOCS, num_slots=8)
    ops = seqk.make_op_batch(
        [[dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0,
               timestamp=1),
          dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=1,
               timestamp=1),
          dict(kind=int(MessageType.OPERATION), slot=0, client_seq=1,
               ref_seq=1, timestamp=2),
          dict(kind=int(MessageType.OPERATION), slot=1, client_seq=1,
               ref_seq=2, timestamp=3),
          # dup: same client_seq again → ignored
          dict(kind=int(MessageType.OPERATION), slot=1, client_seq=1,
               ref_seq=2, timestamp=4)]
         for _ in range(NUM_DOCS)], NUM_DOCS, k=6)
    return state, ops


def test_sequencer_sharded_matches_unsharded(mesh):
    state, ops = _seq_inputs()
    plain_state, plain_tickets = seqk.process_batch(state, ops)

    s_state = pmesh.shard_state(state, mesh)
    s_ops = pmesh.shard_state(ops, mesh)
    out_state, out_tickets = seqk.process_batch(s_state, s_ops)
    jax.block_until_ready(out_state)

    _assert_match_and_sharded(out_state, plain_state, mesh)
    _assert_match_and_sharded(out_tickets, plain_tickets, mesh)


def test_merge_kernel_sharded_matches_unsharded(mesh):
    rng = np.random.default_rng(7)
    state = mtk.init_state(NUM_DOCS, num_slots=32)
    ops = mtk.make_merge_op_batch(
        [[dict(kind=mtk.MT_INSERT, pos=0, seq=1, ref_seq=0, client=0,
               pool_start=0, text_len=12),
          dict(kind=mtk.MT_INSERT, pos=int(rng.integers(0, 12)), seq=2,
               ref_seq=1, client=1, pool_start=12, text_len=6),
          dict(kind=mtk.MT_REMOVE, pos=1, end=4, seq=3, ref_seq=2,
               client=0)]
         for _ in range(NUM_DOCS)], NUM_DOCS, k=4)

    plain = mtk.apply_tick(state, ops)
    out = mtk.apply_tick(pmesh.shard_state(state, mesh),
                         pmesh.shard_state(ops, mesh))
    jax.block_until_ready(out)
    _assert_match_and_sharded(out, plain, mesh)


def test_map_kernel_sharded_matches_unsharded(mesh):
    state = mk.init_state(NUM_DOCS, num_slots=16)
    ops = mk.make_map_op_batch(
        [[dict(kind=mk.MAP_SET, slot=3, value=41, seq=1),
          dict(kind=mk.MAP_SET, slot=3, value=42, seq=2),
          dict(kind=mk.MAP_DELETE, slot=5, seq=3)]
         for _ in range(NUM_DOCS)], NUM_DOCS, k=4)

    plain = mk.apply_tick(state, ops)
    out = mk.apply_tick(pmesh.shard_state(state, mesh),
                        pmesh.shard_state(ops, mesh))
    jax.block_until_ready(out)
    _assert_match_and_sharded(out, plain, mesh)


def test_aggregate_metrics_psum(mesh):
    state, ops = _seq_inputs()
    s_state, s_ops = (pmesh.shard_state(state, mesh),
                      pmesh.shard_state(ops, mesh))
    out_state, tickets = seqk.process_batch(s_state, s_ops)

    totals = pmesh.aggregate_metrics(
        mesh, {"seq": out_state.seq,
               "sequenced": (tickets.kind == 1).astype(jnp.int32)})
    # 4 revs per doc (2 joins + 2 ops; the dup is ignored).
    assert int(totals["seq"]) == NUM_DOCS * 4
    # sequenced tickets: [B, K] leaf reduces over docs leaving [K] — sum it.
    assert int(jnp.sum(totals["sequenced"])) == NUM_DOCS * 4
    # Result is replicated (a true all-reduce), not sharded.
    assert len(_devices_holding(totals["seq"])) == mesh.devices.size


def test_dryrun_impl_runs_on_virtual_mesh():
    import __graft_entry__ as g

    g._dryrun_impl(8)
