"""KernelMergeHost: the merge/map kernels serving behind the server.

The north-star wiring (BASELINE.json): converged server-side state for
SharedString + SharedMap documents is produced by the batched device
kernels, fed from the live sequenced stream, and must match the client
replicas byte-for-byte — including under capacity pressure (compaction,
slot growth) and client-slot overflow (scalar rerouting).
"""

import random

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
)
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.routerlicious import RouterliciousService
from tests.test_matrix import get_matrix, grid_of
from tests.test_mergetree import random_edit


def make_doc(server, doc_id):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("text", SharedString.channel_type)
    datastore.create_channel("root", SharedMap.channel_type)
    container.attach()
    return container


def get_parts(container):
    datastore = container.runtime.get_datastore("default")
    return datastore.get_channel("text"), datastore.get_channel("root")


def run_farm(server, host, rng, n_docs=2, n_clients=3, rounds=4):
    docs = []
    for d in range(n_docs):
        c1 = make_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(n_clients - 1)]
        docs.append([c1] + others)

    for _round in range(rounds):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 8)):
                c = containers[rng.randrange(len(containers))]
                text, root = get_parts(c)
                if rng.random() < 0.6:
                    random_edit(rng, text)
                else:
                    r = rng.random()
                    if r < 0.6:
                        root.set(f"k{rng.randrange(6)}", rng.randrange(100))
                    elif r < 0.85:
                        root.delete(f"k{rng.randrange(6)}")
                    else:
                        root.clear()
            for c in paused:
                c.inbound.resume()

    # Replicas converged (the oracle) — then the device replica must match.
    for d, containers in enumerate(docs):
        texts = [get_parts(c)[0].get_text() for c in containers]
        maps = [dict(get_parts(c)[1].data.items()) for c in containers]
        assert all(t == texts[0] for t in texts)
        assert all(m == maps[0] for m in maps)
        assert host.text(f"doc{d}", "default", "text") == texts[0], d
        assert host.map_entries(f"doc{d}", "default", "root") == maps[0], d


@pytest.mark.parametrize("seed", range(3))
def test_local_server_device_replica_matches_clients(seed):
    host = KernelMergeHost(flush_threshold=16)
    server = LocalCollabServer(merge_host=host)
    run_farm(server, host, random.Random(seed))
    assert host.stats["device_ops"] > 0


def test_routerlicious_merger_lambda_matches_clients():
    host = KernelMergeHost(flush_threshold=10_000)  # ticks via checkpoints
    server = RouterliciousService(merge_host=host)
    run_farm(server, host, random.Random(7))
    # The merger lambda's checkpoint cadence flushed the host (flush
    # threshold was never crossed).
    assert host.stats["device_ops"] > 0


def test_routerlicious_restart_rebuilds_fresh_host_from_op_log():
    """The host is memory-only; a restarted service with a fresh host must
    rebuild the device replica from the scriptorium durable log (the merger
    lambda replays it on creation)."""
    host1 = KernelMergeHost(flush_threshold=16)
    server1 = RouterliciousService(merge_host=host1)
    run_farm(server1, host1, random.Random(11), n_docs=2)
    expected = {d: host1.text(f"doc{d}", "default", "text")
                for d in range(2)}
    maps = {d: host1.map_entries(f"doc{d}", "default", "root")
            for d in range(2)}

    host2 = KernelMergeHost(flush_threshold=16)
    server2 = RouterliciousService(bus=server1.bus, store=server1.store,
                                   merge_host=host2)
    # Documents load lazily: touching each doc (a reconnecting client)
    # instantiates its merger lambda, which replays the durable log.
    for d in range(2):
        server2.connect(f"doc{d}", lambda msgs: None)
    for d in range(2):
        assert host2.text(f"doc{d}", "default", "text") == expected[d]
        assert host2.map_entries(f"doc{d}", "default", "root") == maps[d]


@pytest.mark.soak  # ~80s: growth/compaction pressure sweep
@pytest.mark.slow
def test_capacity_pressure_compacts_and_grows():
    host = KernelMergeHost(merge_slots=8, map_slots=4, num_props=1,
                           flush_threshold=4)
    server = LocalCollabServer(merge_host=host)
    rng = random.Random(3)
    c1 = make_doc(server, "doc")
    c2 = Container.load(LocalDocumentService(server, "doc"))
    for _ in range(120):
        c = c1 if rng.random() < 0.5 else c2
        text, root = get_parts(c)
        random_edit(rng, text)
        root.set(f"key{rng.randrange(12)}", rng.randrange(10))
    t1, m1 = get_parts(c1)
    t2, m2 = get_parts(c2)
    assert t1.get_text() == t2.get_text()
    assert host.text("doc", "default", "text") == t1.get_text()
    assert host.map_entries("doc", "default", "root") == dict(m1.data.items())
    assert (host.stats["compactions"] > 0
            or host.stats["migrations"] > 0
            or any(p.slots > 8 for p in host._merge_pools.values()))
    assert host._map_slots > 4  # 12 keys forced map slot growth


@pytest.mark.soak  # ~65s: cross-bucket migration sweep
@pytest.mark.slow
def test_bucketed_pools_isolate_large_documents():
    """Ragged batching: one hot channel migrating to a bigger bucket must
    not widen the small channels' segment table (SURVEY §5.7)."""
    host = KernelMergeHost(merge_slots=8, num_props=1, flush_threshold=8)
    server = LocalCollabServer(merge_host=host)
    big = make_doc(server, "big")
    small = make_doc(server, "small")
    big_text, _ = get_parts(big)
    small_text, _ = get_parts(small)
    small_text.insert_text(0, "tiny")
    # Interleave positions so zamboni can't fully pack the big doc; msn
    # pinned low by a second (idle) client would also work, but distinct
    # inserts at position 0 keep every segment live anyway.
    for i in range(80):
        big_text.insert_text(i % max(len(big_text.get_text()), 1), "xy")
    host.flush()
    assert host.text("big", "default", "text") == big_text.get_text()
    assert host.text("small", "default", "text") == "tiny"
    big_row = host._merge_rows[("big", "default", "text")]
    small_row = host._merge_rows[("small", "default", "text")]
    assert big_row.pool is not small_row.pool
    assert small_row.pool.slots == 8  # small docs still pay the small bill
    assert big_row.pool.slots > 8
    assert host.stats["migrations"] > 0
    # And the migrated row keeps converging.
    big_text.insert_text(0, "Z")
    host.flush()
    assert host.text("big", "default", "text") == big_text.get_text()


@pytest.mark.parametrize("seed", range(3))
def test_matrix_channels_served_by_device_kernel(seed):
    """SharedMatrix docs behind the service: device grid == every replica
    (matrix.ts:547 hosted — the remaining north-star processCore path)."""
    from tests.test_matrix_kernel import random_matrix_edit

    host = KernelMergeHost(flush_threshold=16)
    server = LocalCollabServer(merge_host=host)
    rng = random.Random(seed)
    c1 = Container.create_detached(LocalDocumentService(server, "doc"))
    c1.runtime.create_datastore("default").create_channel(
        "grid", SharedMatrix.channel_type)
    c1.attach()
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m1 = get_matrix(c1)
    m2 = get_matrix(c2)
    m1.insert_rows(0, 2)
    m1.insert_cols(0, 2)
    for _ in range(60):
        random_matrix_edit(rng, m1 if rng.random() < 0.5 else m2)
    assert grid_of(m1) == grid_of(m2)
    assert host.matrix_grid("doc", "default", "grid") == grid_of(m1)
    summary = host.summarize("doc")
    assert summary["datastores"]["default"]["grid"]["kind"] == "matrix"
    assert summary["datastores"]["default"]["grid"]["grid"] == grid_of(m1)


def test_matrix_client_overflow_routes_to_scalar():
    host = KernelMergeHost(flush_threshold=4, max_client_slots=32)
    server = LocalCollabServer(merge_host=host)
    c1 = Container.create_detached(LocalDocumentService(server, "doc"))
    c1.runtime.create_datastore("default").create_channel(
        "grid", SharedMatrix.channel_type)
    c1.attach()
    m1 = get_matrix(c1)
    m1.insert_rows(0, 1)
    m1.insert_cols(0, 1)
    # More clients than the configured ceiling → scalar rerouting.
    replicas = [Container.load(LocalDocumentService(server, "doc"))
                for _ in range(host.max_client_slots + 1)]
    for i, c in enumerate(replicas):
        get_matrix(c).set_cell(0, 0, i)
    assert host.stats["overflow_routed"] > 0
    assert grid_of(m1) == grid_of(get_matrix(replicas[-1]))
    assert host.matrix_grid("doc", "default", "grid") == grid_of(m1)
    # The scalar-served channel keeps tracking later edits.
    m1.insert_cols(1, 1)
    m1.set_cell(0, 1, "post")
    assert host.matrix_grid("doc", "default", "grid") == grid_of(m1)


def _op_message(seq, ref_seq, client_id, channel_op, msn=0):
    return SequencedDocumentMessage(
        client_id=client_id,
        sequence_number=seq,
        minimum_sequence_number=msn,
        client_sequence_number=seq,
        reference_sequence_number=ref_seq,
        type=MessageType.OPERATION,
        contents={"address": "default",
                  "contents": {"address": "text", "contents": channel_op}},
        timestamp=seq,
        data=None,
    )


def test_client_slot_overflow_routes_to_scalar():
    """More distinct writers than the configured ceiling → scalar
    rerouting, with the full history replayed and later ops served."""
    host = KernelMergeHost(merge_slots=256, flush_threshold=8,
                           max_client_slots=32)
    n_clients = host.max_client_slots + 5
    seq = 0
    for i in range(n_clients):
        seq += 1
        host.ingest("doc", _op_message(
            seq, seq - 1, f"c{i}",
            {"type": "insert", "pos": 0, "text": f"<{i}>"}))
    expected = "".join(f"<{i}>" for i in reversed(range(n_clients)))
    assert host.text("doc", "default", "text") == expected
    assert host.stats["overflow_routed"] == 1
    assert host.stats["scalar_ops"] > 0
    # Ops after the reroute apply through the scalar engine.
    seq += 1
    host.ingest("doc", _op_message(seq, seq - 1, "c0",
                                   {"type": "remove", "start": 0, "end": 4}))
    assert host.text("doc", "default", "text") == expected[4:]


@pytest.mark.soak  # ~70s: 6000-op memory-bound soak
@pytest.mark.slow
def test_soak_host_memory_bounded(monkeypatch):
    """Long-lived channel: the replay log trims at every flush and the
    text pool repacks, so host memory stays bounded by the flush cadence
    + live content — not by total history (VERDICT r2 weak #5)."""
    from fluidframework_tpu.server import merge_host as mh

    monkeypatch.setattr(mh, "_TEXT_REPACK_MIN", 4096)
    host = KernelMergeHost(merge_slots=64, flush_threshold=64)
    key = ("doc", "default", "text")
    seq = 0
    rng = random.Random(0)
    max_log = 0
    for i in range(6000):
        seq += 1
        if rng.random() < 0.6:
            op = {"type": "insert", "pos": 0, "text": "abcdefgh"}
        else:
            op = {"type": "remove", "start": 0, "end": 4}
        host.ingest("doc", _op_message(seq, seq - 1, f"c{i % 4}", op,
                                       msn=seq - 1))
        max_log = max(max_log, len(host._merge_rows[key].raw_log))
    host.flush()
    row = host._merge_rows[key]
    # ~48k chars inserted over the run; the log never exceeds one flush
    # window and the pool holds only (re-packable) referenced slices.
    assert max_log <= 2 * host.flush_threshold
    assert len(row.raw_log) == 0
    assert row.pool.text.used[row.row] < 40_000
    assert host.stats["compactions"] > 0
    # State stayed exact throughout.
    oracle = __import__(
        "fluidframework_tpu.dds.mergetree",
        fromlist=["MergeEngine"]).MergeEngine()
    rng = random.Random(0)
    s = 0
    for i in range(6000):
        s += 1
        if rng.random() < 0.6:
            oracle.apply_remote({"type": "insert", "pos": 0,
                                 "text": "abcdefgh"}, s, s - 1, f"c{i % 4}")
        else:
            oracle.apply_remote({"type": "remove", "start": 0, "end": 4},
                                s, s - 1, f"c{i % 4}")
    assert host.text(*key) == oracle.get_text()


def test_overflow_after_trimmed_log_seeds_from_device():
    """Slot overflow long after the replay log was trimmed: the scalar
    engine must seed EXACTLY from the device row (segments, tombstones,
    props) + the unapplied tail — full history is gone."""
    host = KernelMergeHost(merge_slots=256, flush_threshold=8,
                           max_client_slots=32)
    oracle = __import__(
        "fluidframework_tpu.dds.mergetree",
        fromlist=["MergeEngine"]).MergeEngine()
    seq = 0

    def both(op, client):
        nonlocal seq
        seq += 1
        host.ingest("doc", _op_message(seq, seq - 1, client, op))
        oracle.apply_remote(op, seq, seq - 1, client)

    rng = random.Random(1)
    for i in range(60):  # many flushes -> raw_log trimmed repeatedly
        both({"type": "insert", "pos": rng.randrange(i * 3 + 1),
              "text": f"<{i}>"}, f"c{i % 4}")
    both({"type": "annotate", "start": 0, "end": 10,
          "props": {"bold": True}}, "c0")
    key = ("doc", "default", "text")
    assert len(host._merge_rows[key].raw_log) < 60
    # Now blow the client-slot ceiling.
    for i in range(host.max_client_slots + 2):
        both({"type": "insert", "pos": 0, "text": f"[{i}]"}, f"x{i}")
    assert host.stats["overflow_routed"] == 1
    assert host.text(*key) == oracle.get_text()
    # Scalar-served continues exactly.
    both({"type": "remove", "start": 2, "end": 9}, "x0")
    both({"type": "insert", "pos": 4, "text": "tail"}, "c1")
    assert host.text(*key) == oracle.get_text()
    runs = host.rich_text(*key)
    assert any(props == {"bold": True} for _, props in runs)


def test_scalar_channel_readmitted_to_device():
    """The overflow escape is not one-way (VERDICT r2 weak #7): once the
    departed writers' segments compact away (window advance), the channel
    re-encodes onto a device row and serves on device again — exactly."""
    host = KernelMergeHost(merge_slots=256, flush_threshold=8,
                           max_client_slots=32)
    oracle = __import__(
        "fluidframework_tpu.dds.mergetree",
        fromlist=["MergeEngine"]).MergeEngine()
    seq = 0

    def both(op, client, msn=None):
        nonlocal seq
        seq += 1
        host.ingest("doc", _op_message(seq, seq - 1, client, op,
                                       msn=msn if msn is not None
                                       else seq - 1))
        oracle.apply_remote(op, seq, seq - 1, client)
        oracle.update_min_seq(msn if msn is not None else seq - 1)

    # Blow the ceiling: 37 distinct writers, one insert each at pos 0.
    n_writers = host.max_client_slots + 5
    for i in range(n_writers):
        both({"type": "insert", "pos": 0, "text": f"<{i}>"}, f"w{i}")
    key = ("doc", "default", "text")
    assert host.stats["overflow_routed"] == 1
    assert host._merge_rows[key].scalar is not None

    # Two surviving clients remove everything the departed writers wrote
    # and keep editing; the window advances past the removals, zamboni
    # compacts the old writers' segments away.
    text_len = len(host.text(*key))
    both({"type": "remove", "start": 0, "end": text_len}, "keeper-a")
    both({"type": "insert", "pos": 0, "text": "fresh "}, "keeper-b")
    both({"type": "annotate", "start": 0, "end": 5,
          "props": {"kept": True}}, "keeper-a", msn=seq)
    both({"type": "insert", "pos": 6, "text": "start"}, "keeper-a",
         msn=seq)
    host.flush()
    row = host._merge_rows[key]
    assert host.stats["readmissions"] == 1
    assert row.scalar is None and row.pool is not None
    assert host.text(*key) == oracle.get_text() == "fresh start"

    # Device-served again: later ops run through the kernel and match.
    device_before = host.stats["device_ops"]
    both({"type": "insert", "pos": 5, "text": "er"}, "keeper-b", msn=seq)
    both({"type": "remove", "start": 0, "end": 2}, "keeper-a", msn=seq)
    assert host.text(*key) == oracle.get_text()
    assert host.stats["device_ops"] > device_before
    runs = host.rich_text(*key)
    assert any(props == {"kept": True} for _, props in runs)


def test_128_writers_device_served():
    """BASELINE config 2's shape — 1 doc x 128 distinct writers — stays
    ON the device path: the overlap planes grow (32 slots/word -> 4
    words), nothing routes to scalar, and the converged text is
    byte-identical to the scalar oracle. Matches the reference's client
    scale (config.json:39 allows 1M clients/doc; conflictFarm.spec.ts
    stresses 32)."""
    host = KernelMergeHost(merge_slots=256, flush_threshold=16)
    oracle = __import__(
        "fluidframework_tpu.dds.mergetree",
        fromlist=["MergeEngine"]).MergeEngine()
    rng = random.Random(7)
    seq = 0
    n_writers = 128
    for i in range(n_writers):
        seq += 1
        op = {"type": "insert", "pos": rng.randrange(3 * i + 1),
              "text": f"<{i}>"}
        host.ingest("doc", _op_message(seq, seq - 1, f"w{i}", op))
        oracle.apply_remote(op, seq, seq - 1, f"w{i}")
    # Interleaved concurrent removes/annotates from every writer band so
    # the overlap planes actually carry bits in words 1-3.
    for i in range(0, n_writers, 7):
        seq += 1
        op = {"type": "remove", "start": i, "end": i + 3}
        host.ingest("doc", _op_message(seq, seq - 8, f"w{i}", op))
        oracle.apply_remote(op, seq, seq - 8, f"w{i}")
    key = ("doc", "default", "text")
    host.flush()
    row = host._merge_rows[key]
    assert host.stats["overflow_routed"] == 0
    assert host.stats["scalar_ops"] == 0
    assert host.stats["device_ops"] > 0
    assert row.scalar is None
    assert row.pool.client_capacity >= n_writers
    assert host.text(*key) == oracle.get_text()
    # Overlap-remove concurrency across high slots resolves identically.
    for i in (40, 80, 120):
        seq += 1
        op = {"type": "remove", "start": 0, "end": 2}
        host.ingest("doc", _op_message(seq, seq - 3, f"w{i}", op))
        oracle.apply_remote(op, seq, seq - 3, f"w{i}")
    assert host.text(*key) == oracle.get_text()
    assert host.stats["overflow_routed"] == 0


def test_annotate_and_markers_materialize():
    host = KernelMergeHost(flush_threshold=100)
    server = LocalCollabServer(merge_host=host)
    c1 = make_doc(server, "doc")
    text, _ = get_parts(c1)
    text.insert_text(0, "hello world")
    text.annotate_range(0, 5, {"bold": True})
    text.insert_marker(5, ref_type="tile", marker_id="m1")
    assert host.text("doc", "default", "text") == "hello world"
    runs = host.rich_text("doc", "default", "text")
    assert ("hello", {"bold": True}) in runs
    assert ("\x00", None) in runs


def test_summarize_materializes_from_device():
    host = KernelMergeHost(flush_threshold=100)
    server = LocalCollabServer(merge_host=host)
    c1 = make_doc(server, "doc")
    text, root = get_parts(c1)
    text.insert_text(0, "abc")
    root.set("x", 1)
    summary = host.summarize("doc")
    channels = summary["datastores"]["default"]
    assert channels["text"]["kind"] == "mergeTree"
    assert "".join(t for t, _ in channels["text"]["content"]) == "abc"
    assert channels["root"]["entries"] == {"x": 1}
    assert summary["sequence_number"] > 0


def test_long_lived_doc_stays_in_bucket_via_coalesce():
    """A long-lived document whose window keeps advancing must NOT climb
    buckets forever: under capacity pressure the host repacks the text
    pool and runs the coalescing zamboni, so slot demand tracks the
    collab window, not total history (mergeTree.ts:1412 pack analog)."""
    host = KernelMergeHost(merge_slots=64, flush_threshold=48)
    oracle = __import__(
        "fluidframework_tpu.dds.mergetree",
        fromlist=["MergeEngine"]).MergeEngine()
    rng = random.Random(3)
    seq = 0
    length = 0
    for i in range(3000):
        seq += 1
        if length > 30 and rng.random() < 0.45:
            start = rng.randrange(length - 8)
            op = {"type": "remove", "start": start,
                  "end": start + rng.randrange(1, 9)}
            length -= op["end"] - op["start"]
        else:
            text = "abcdefgh"[:rng.randrange(1, 8)]
            op = {"type": "insert", "pos": rng.randrange(length + 1),
                  "text": text}
            length += len(text)
        host.ingest("doc", _op_message(seq, seq - 1, f"c{i % 4}", op,
                                       msn=seq - 1))
        oracle.apply_remote(op, seq, seq - 1, f"c{i % 4}")
        oracle.update_min_seq(seq - 1)
    host.flush()
    key = ("doc", "default", "text")
    row = host._merge_rows[key]
    # ~1650 inserts x 2 slots would demand a 8192-slot bucket without
    # coalescing; the window is 1 op deep, so the table stays small.
    assert row.pool.slots <= 256, row.pool.slots
    assert host.stats["compactions"] > 0
    assert host.text(*key) == oracle.get_text()


def test_matrix_cell_run_fast_path_with_compaction():
    """A settled grid under cell-write storms takes the scan-free
    cell-run tile path; the append log dedups under capacity pressure;
    a later structural op falls back to the exact per-op path and still
    converges (mixed-path composition)."""
    host = KernelMergeHost(flush_threshold=8)
    server = LocalCollabServer(merge_host=host)
    rng = random.Random(3)
    c1 = Container.create_detached(LocalDocumentService(server, "doc"))
    c1.runtime.create_datastore("default").create_channel(
        "grid", SharedMatrix.channel_type)
    c1.attach()
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m1, m2 = get_matrix(c1), get_matrix(c2)
    m1.insert_rows(0, 8)
    m1.insert_cols(0, 8)
    host.flush()
    # Cell-only storm: repeated keys force log growth + dedup compaction.
    for _ in range(40):
        m = m1 if rng.random() < 0.5 else m2
        m.set_cell(rng.randrange(8), rng.randrange(4), rng.randrange(99))
    host.flush()
    assert host.stats.get("cell_run_ticks", 0) > 0, "fast path never taken"
    assert grid_of(m1) == grid_of(m2)
    assert host.matrix_grid("doc", "default", "grid") == grid_of(m1)
    # Structural op -> per-op fallback; cells after it still converge.
    m1.insert_rows(2, 1)
    for _ in range(12):
        m2.set_cell(rng.randrange(9), rng.randrange(8), rng.randrange(99))
    host.flush()
    assert grid_of(m1) == grid_of(m2)
    assert host.matrix_grid("doc", "default", "grid") == grid_of(m1)
