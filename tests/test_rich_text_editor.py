"""Editor-grade shared-text scenario (VERDICT r4 missing #4 / next #9).

The reference's flagship app class is real rich text
(examples/data-objects/shared-text + webflow/prosemirror integrations):
marker-structured paragraphs + formatting annotates + interval comments,
all riding one SharedString through a live service. These scenarios
drive that COMBINED shape — the one that stresses annotate planes,
markers and interval rebinds together — through the device-served
merge host with multiple clients, asserting structured-render equality,
not just text equality.
"""

import random

import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.examples import host as example_host
from fluidframework_tpu.runtime.loader import Loader
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost
from tests.test_beast import load_corpus

URL = "fluid://localhost/rich-doc"


def _open_editor_doc(server):
    loader = Loader(lambda doc: LocalDocumentService(server, doc),
                    example_host.build_code_loader())
    _container, editor = example_host.create_document(
        loader, "@examples/rich-text-editor", URL,
        props={"initial_text": ""})
    return editor, loader


def _join(loader):
    _container, editor = example_host.open_existing(loader, URL)
    return editor


def test_two_editors_converge_structured():
    host = KernelMergeHost(flush_threshold=64)
    server = LocalCollabServer(merge_host=host)
    e1, loader = _open_editor_doc(server)
    e1.type_text(1, "The opening paragraph about TPU serving.")
    e2 = _join(loader)

    # Concurrent structure + formatting + comments.
    e1.set_format(5, 12, bold=True)
    pid = e2.split_paragraph(len(e2.read()))
    e2.type_text(len(e2.read()), "A second paragraph from client two.")
    e1.add_comment(5, 12, "headline")
    e2.set_format(1, 4, em=True)
    host.flush()
    assert e1.render() == e2.render()
    assert any(p["id"] == pid for p in e1.render())
    assert e1.comments_overlapping(0, len(e1.read())) == \
        e2.comments_overlapping(0, len(e2.read()))

    # Comments ride concurrent edits BEFORE their anchor.
    (start, end, note), = e1.comments_overlapping(0, len(e1.read()))
    e2.type_text(1, "xxxxx ")
    host.flush()
    (s2, e2_, n2), = e1.comments_overlapping(0, len(e1.read()))
    assert (s2, e2_, n2) == (start + 6, end + 6, note)
    assert e1.render() == e2.render()


@pytest.mark.parametrize("seed", [11])
def test_editor_corpus_farm(seed):
    """The beastTest corpus streamed through the EDITOR surface: typed
    prose + paragraph breaks + formatting + comments from several
    clients, device-served, structured render converging."""
    words = load_corpus(40_000)
    rng = random.Random(seed)
    host = KernelMergeHost(flush_threshold=128)
    server = LocalCollabServer(merge_host=host)
    first, loader = _open_editor_doc(server)
    editors = [first]
    for _ in range(3):
        editors.append(_join(loader))

    cursor = 0
    live_comments: list[str] = []
    for step in range(600):
        ed = editors[rng.randrange(len(editors))]
        length = len(ed.text)  # position space includes markers
        roll = rng.random()
        if roll < 0.55 or length < 64:
            n = rng.randrange(1, 7)
            span = " ".join(words[(cursor + i) % len(words)]
                            for i in range(n)) + " "
            cursor += n
            ed.type_text(rng.randrange(1, length + 1), span)
        elif roll < 0.70:
            start = rng.randrange(1, length - 16)
            ed.delete(start, start + rng.randrange(1, 24))
        elif roll < 0.82:
            start = rng.randrange(1, length - 8)
            ed.set_format(start, start + rng.randrange(1, 12),
                          bold=bool(step % 2), style=step % 5)
        elif roll < 0.92:
            ed.split_paragraph(rng.randrange(1, length + 1))
        else:
            start = rng.randrange(1, length - 8)
            cid = ed.add_comment(start, start + rng.randrange(1, 8),
                                 f"note-{step}")
            live_comments.append(cid)
            if len(live_comments) > 8:
                victim = live_comments.pop(0)
                ed2 = editors[rng.randrange(len(editors))]
                try:
                    ed2.resolve_comment(victim)
                except KeyError:
                    pass
    host.flush()
    renders = [ed.render() for ed in editors]
    texts = [ed.read() for ed in editors]
    assert all(t == texts[0] for t in texts[1:])
    assert all(r == renders[0] for r in renders[1:]), "renders diverged"
    # The farm actually exercised the combined shape.
    assert len(renders[0]) > 10, "no paragraph structure built"
    assert any(p["comments"] for p in renders[0]) or live_comments
    assert any(style for _text, style in
               (run for p in renders[0] for run in p["runs"]))
    # Device-served end to end: no scalar fallback engaged.
    assert host.scalar_fraction() == 0.0
