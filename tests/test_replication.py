"""Replication plane (round 19 tentpole, server/replication.py):
quorum-shipped WAL batches, replicated head flips, and leader failover.

The acceptance bars under test here, in-process (the kill -9 recovery
story rides tests/test_chaos.py's REPLICATION smoke + soak):

* **stream hygiene** — torn, reordered and duplicated shipped batches
  never corrupt a follower's replica log: torn payloads reject whole,
  gaps nack with the follower's length so the leader re-ships the
  missing tail, duplicates ack idempotently;
* **quorum gating** — client acks advance on ``min(durable,
  replicated)``: a partitioned quorum freezes the watermark (and with
  it the acks) while local durability keeps going, and heals through
  the gap-nack → resync path once the link returns;
* **restart / lag resync** — a follower restarted mid-stream resumes
  from its on-disk log; one whose lag crossed the history plane's
  retention floor converges on journaled heads (snapshot) + log tail,
  receiving the same filler bytes the leader holds;
* **ship-then-flip heads** — a backend head only ever flips after a
  follower quorum journaled it, so promotion can roll every journal
  forward without ever rolling the backend back;
* **promotion + fencing** — the most advanced follower becomes a
  serving host byte-equal on every converged plane, and the demoted
  ex-leader sheds all traffic with ``moved`` nacks, refuses
  checkpoints, and never acks again.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from fluidframework_tpu.parallel.placement import (
    StormCluster,
    make_cluster_host,
)
from fluidframework_tpu.protocol.codec import (
    decode_storm_body,
    encode_storm_body,
)
from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.history import HistoryPlane
from fluidframework_tpu.server.historian import Historian
from fluidframework_tpu.server.replication import (
    REPLICATION_STREAM_VERSION,
    ReplicaLink,
    ReplicaNode,
    ReplicatedHeadStore,
    ReplicationPlane,
    ReplicationQuorumError,
    _frame,
    choose_promotion_candidate,
    make_replicated_host,
    promote,
    promote_heads,
)

K = 8


def _words(seed, k=K):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 0, 1], size=k).astype(np.uint32)  # set/del
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _build(tmp_path, followers=1, acks_required=None, label="hostA",
           num_docs=8):
    git = GitSnapshotStore(str(tmp_path / "git"))
    f_dirs = [str(tmp_path / f"f{i}") for i in range(followers)]
    storm, plane = make_replicated_host(
        label, str(tmp_path / label), git, f_dirs,
        acks_required=acks_required, num_docs=num_docs)
    return git, storm, plane


def _serve(storm, docs, rounds, cseq=None, clients=None, seed=3, k=K,
           sink=None):
    if clients is None:
        clients = {d: storm.service.connect(d, lambda m: None).client_id
                   for d in docs}
        storm.service.pump()
    cseq = cseq if cseq is not None else {d: 1 for d in docs}
    for r in range(rounds):
        for i, d in enumerate(docs):
            w = _words([seed, cseq[d], i], k)
            storm.submit_frame(
                sink or (lambda p: None),
                {"rid": (cseq[d], d),
                 "docs": [[d, clients[d], cseq[d], 1, k]]},
                memoryview(w.tobytes()))
            cseq[d] += k
        storm.flush()
    return clients, cseq


def _entries(storm, docs):
    return {d: storm.merge_host.map_entries(d, storm.datastore,
                                            storm.channel)
            for d in docs}


def _close(storm):
    if storm._group_wal is not None:
        storm._group_wal.close()


# -- shipped-batch stream hygiene (torn / reordered / duplicated) --------------


class TestStreamEdgeCases:

    def test_torn_payload_rejected_whole(self, tmp_path):
        """A frame whose lens claim more record bytes than arrived is
        refused before ANY append — a partial append would CRC-frame
        garbage at a real index and poison every later read."""
        node = ReplicaNode(tmp_path / "f")
        torn = _frame("batch", {"seq": 0, "lens": [4, 4]}, b"only5")
        hdr, _ = decode_storm_body(node.on_frame(torn))
        assert hdr["k"] == "nack" and hdr["reason"] == "torn-payload"
        assert node.log_len == 0 and node.stats["rejected"] == 1
        # The same records delivered whole land fine afterwards.
        good = _frame("batch", {"seq": 0, "lens": [4, 4]}, b"aaaabbbb")
        hdr2 = ReplicaLink(node).call(good)
        assert hdr2["k"] == "ack" and hdr2["len"] == 2
        assert node.read(0) == b"aaaa" and node.read(1) == b"bbbb"

    def test_truncated_frame_on_the_wire_rejected(self, tmp_path):
        """Byte-level truncation in transit (ReplicaLink.transform):
        the codec framing itself fails and the follower nacks without
        touching its log."""
        node = ReplicaNode(tmp_path / "f")
        link = ReplicaLink(node)
        link.transform = lambda b: b[:max(1, len(b) // 2)]
        hdr = link.call(_frame("batch", {"seq": 0, "lens": [3]}, b"abc"))
        assert hdr["k"] == "nack" and node.log_len == 0

    def test_reordered_batch_gap_nacks_with_local_length(self, tmp_path):
        """A batch arriving ahead of its predecessor (reordered or the
        predecessor lost) is refused; the nack carries the follower's
        length so the leader knows where the missing tail starts."""
        node = ReplicaNode(tmp_path / "f")
        link = ReplicaLink(node)
        hdr = link.call(_frame("batch", {"seq": 5, "lens": [3]}, b"abc"))
        assert hdr["k"] == "nack" and hdr["reason"] == "gap"
        assert hdr["len"] == 0 and node.stats["gap_nacks"] == 1
        assert node.log_len == 0

    def test_duplicate_and_overlapping_batches_idempotent(self, tmp_path):
        """Exact duplicates ack without re-appending; an overlapping
        re-ship (retry straddling the follower's length) appends only
        the genuinely new suffix."""
        node = ReplicaNode(tmp_path / "f")
        link = ReplicaLink(node)
        link.call(_frame("batch", {"seq": 0, "lens": [2, 2]}, b"aabb"))
        # Exact duplicate delivery.
        hdr = link.call(_frame("batch", {"seq": 0, "lens": [2, 2]},
                               b"aabb"))
        assert hdr["k"] == "ack" and hdr["len"] == 2
        assert node.stats["dup_records"] == 2
        # Overlap: records 1-2 where record 1 is already journaled.
        hdr = link.call(_frame("batch", {"seq": 1, "lens": [2, 2]},
                               b"bbcc"))
        assert hdr["k"] == "ack" and hdr["len"] == 3
        assert [node.read(i) for i in range(3)] == [b"aa", b"bb", b"cc"]

    def test_newer_stream_version_refused(self, tmp_path):
        node = ReplicaNode(tmp_path / "f")
        frame = encode_storm_body(
            {"v": REPLICATION_STREAM_VERSION + 1, "k": "batch",
             "seq": 0, "lens": [1]}, b"x")
        hdr = ReplicaLink(node).call(frame)
        assert hdr["k"] == "nack" and hdr["reason"] == "version"
        assert node.log_len == 0

    def test_head_flips_journal_monotonic_and_survive_reopen(
            self, tmp_path):
        """Duplicate/old head flips are idempotent; the journal reloads
        from disk (the restart half of promotion's roll-forward)."""
        node = ReplicaNode(tmp_path / "f")
        link = ReplicaLink(node)
        link.call(_frame("head", {"hseq": 1, "key": "a", "handle": "h1"}))
        link.call(_frame("head", {"hseq": 2, "key": "a", "handle": "h2"}))
        # Replayed old flip: refused silently (idempotent ack).
        hdr = link.call(_frame("head",
                               {"hseq": 1, "key": "a", "handle": "h1"}))
        assert hdr["k"] == "ack" and hdr["hseq"] == 2
        assert node.heads["a"] == (2, "h2")
        node.close()
        again = ReplicaNode(tmp_path / "f")
        assert again.heads["a"] == (2, "h2") and again.max_hseq == 2


# -- quorum watermark gating ---------------------------------------------------


class TestQuorumGating:

    def test_replicated_watermark_tracks_durable_f1(self, tmp_path):
        """F=1 healthy: every fsynced batch ships synchronously, so the
        replicated watermark equals the durable one after each flush
        and the storm's ack gate never withholds."""
        _git, storm, plane = _build(tmp_path, followers=1)
        _serve(storm, ["doc-0", "doc-1"], rounds=3)
        assert storm._group_wal.durable_len > 0
        assert plane.replicated_len == storm._group_wal.durable_len
        assert storm.acked_watermark == storm._group_wal.durable_len
        assert plane.follower_lag == 0
        assert plane.stats["batches_shipped"] >= 3
        _close(storm)

    def test_partitioned_quorum_freezes_acks_then_heals(self, tmp_path):
        """The only follower partitioned (F=1): local durability keeps
        advancing but the replicated watermark — and the ack gate —
        freeze. When the link returns, the next ship gap-nacks and the
        resync re-ships the missing tail; acks resume."""
        _git, storm, plane = _build(tmp_path, followers=1)
        clients, cseq = _serve(storm, ["doc-0"], rounds=2)
        frozen = plane.replicated_len
        assert frozen == storm._group_wal.durable_len
        plane.links[0].down = True
        _serve(storm, ["doc-0"], rounds=2, cseq=cseq, clients=clients)
        assert storm._group_wal.durable_len > frozen
        assert plane.replicated_len == frozen  # quorum unreachable
        assert storm.acked_watermark == frozen  # acks withheld
        assert plane.stats["ship_failures"] >= 2
        plane.links[0].down = False
        _serve(storm, ["doc-0"], rounds=1, cseq=cseq, clients=clients)
        assert plane.replicated_len == storm._group_wal.durable_len
        assert storm.acked_watermark == storm._group_wal.durable_len
        assert plane.links[0].node.log_len == plane.replicated_len
        _close(storm)

    def test_f2_majority_tolerates_one_follower_down(self, tmp_path):
        """F=2 with the default majority quorum ((F+1)//2 = 1 follower
        ack): one partitioned follower slows nothing, but shows up as
        follower lag — the resync debt a second failure would cost."""
        _git, storm, plane = _build(tmp_path, followers=2)
        assert plane.acks_required == 1
        plane.links[1].down = True
        _serve(storm, ["doc-0", "doc-1"], rounds=3)
        assert plane.replicated_len == storm._group_wal.durable_len
        assert plane.follower_lag == storm._group_wal.durable_len
        _close(storm)

    def test_chain_replication_waits_for_every_follower(self, tmp_path):
        """acks_required=F (chain-style full replication): ONE follower
        down freezes the watermark even though a majority is healthy."""
        _git, storm, plane = _build(tmp_path, followers=2,
                                    acks_required=2)
        plane.links[1].down = True
        _serve(storm, ["doc-0"], rounds=2)
        assert plane.replicated_len == 0
        assert storm.acked_watermark == 0
        _close(storm)

    def test_gauges_reflect_plane_state(self, tmp_path):
        _git, storm, plane = _build(tmp_path, followers=2)
        plane.links[1].down = True
        _serve(storm, ["doc-0"], rounds=2)
        snap = storm.merge_host.metrics.snapshot()
        assert snap["repl.role_code"] == 1  # leader
        assert snap["repl.followers"] == 2
        # Gauges refresh on the ship hook, which runs BEFORE the batch
        # it ships advances the durable watermark — so the sampled lag
        # trails the live property by at most the current batch.
        assert snap["repl.lag"] >= 1
        assert plane.follower_lag == storm._group_wal.durable_len
        assert snap["repl.watermark_gap"] == 0  # majority still acks
        assert snap["repl.shipped_batches"] >= 2
        _close(storm)


# -- follower restart / retention-floor resync ---------------------------------


class TestFollowerResync:

    def test_follower_restart_mid_stream_resumes_from_disk(
            self, tmp_path):
        """Restarted follower (same directory): the replica log and
        head journal reload from disk; batches missed while it was down
        arrive through the gap-nack → tail re-ship path and the logs
        reconverge byte-identical to the leader's."""
        _git, storm, plane = _build(tmp_path, followers=1)
        clients, cseq = _serve(storm, ["doc-0", "doc-1"], rounds=2)
        link = plane.links[0]
        link.down = True  # follower "crashes"
        _serve(storm, ["doc-0", "doc-1"], rounds=2, cseq=cseq,
               clients=clients)
        behind = link.node.log_len
        assert behind < storm._group_wal.durable_len
        # Restart: a fresh ReplicaNode over the same directory.
        link.node.close()
        link.node = ReplicaNode(tmp_path / "f0")
        assert link.node.log_len == behind  # resumed, not reset
        link.down = False
        _serve(storm, ["doc-0", "doc-1"], rounds=1, cseq=cseq,
               clients=clients)
        durable = storm._group_wal.durable_len
        assert link.node.log_len == durable
        assert plane.replicated_len == durable
        assert [link.node.read(i) for i in range(durable)] == \
            [storm._group_wal.read(i) for i in range(durable)]
        assert plane.stats["resyncs"] >= 1
        _close(storm)

    def test_lag_beyond_retention_floor_converges_on_snapshot_plus_tail(
            self, tmp_path):
        """The nasty one: a follower partitioned long enough that the
        history plane TRIMMED ticks it never received. The resync ships
        the same filler bytes the leader now holds; the follower's
        recovery story becomes snapshot (journaled checkpoint heads) +
        log tail — promoting IT must still reproduce the leader's
        converged map state exactly."""
        docs = ["doc-0", "doc-1"]
        git, storm, plane = _build(tmp_path, followers=2)
        hist = HistoryPlane(storm, summary_interval_ops=1,
                            tail_retention_summaries=0,
                            trim_batch_ticks=1)
        clients, cseq = _serve(storm, docs, rounds=2)
        lagger = plane.links[1]
        lagger.down = True  # misses everything from here
        behind = lagger.node.log_len
        _serve(storm, docs, rounds=3, cseq=cseq, clients=clients)
        # Checkpoint (quorum via the healthy follower), then compact +
        # trim everything below it — the records the lagger missed.
        storm.checkpoint()
        for d in docs:
            hist.compact(d)
        hist.trim_now()
        assert hist.stats["trimmed_ticks"] > 0  # fillers on disk now
        lagger.down = False
        _serve(storm, docs, rounds=1, cseq=cseq, clients=clients)
        want = _entries(storm, docs)
        durable = storm._group_wal.durable_len
        assert lagger.node.log_len == durable
        # The MISSED region arrives exactly as the leader now holds it
        # — fillers included. (Records received before the partition
        # keep their original bytes on the follower; the leader later
        # shrank its own copies, which recovery below the checkpoint
        # skips either way.)
        assert [lagger.node.read(i) for i in range(behind, durable)] \
            == [storm._group_wal.read(i) for i in range(behind, durable)]
        assert any(b"trimmed" in lagger.node.read(i)
                   for i in range(behind, durable))
        _close(storm)
        # Promote the PREVIOUSLY-LAGGING follower alone (as if both the
        # leader and the healthy follower died).
        new_storm, _new_plane, report = promote(
            "hostA", [lagger.node], git,
            follower_dirs=[str(tmp_path / "fresh")], num_docs=8)
        assert report["promoted_node"] == "f1"
        assert _entries(new_storm, docs) == want
        _close(new_storm)


# -- replicated head flips (ship-then-flip) ------------------------------------


class TestReplicatedHeads:

    def test_set_head_ships_before_backend_flip(self, tmp_path):
        git, storm, plane = _build(tmp_path, followers=1)
        store = storm.snapshots
        assert isinstance(store, ReplicatedHeadStore)
        handle = git.upload("docX", {"kind": "x", "n": 1})
        store.set_head("docX", handle)
        assert git.head("docX") == handle
        node = plane.links[0].node
        assert node.heads["docX"][1] == handle  # journaled first
        _close(storm)

    def test_quorum_refusal_leaves_backend_untouched(self, tmp_path):
        """An unreachable quorum REFUSES the flip — the backend head
        can never run ahead of every journal (the invariant promotion's
        roll-forward relies on). checkpoint() surfaces the refusal."""
        git, storm, plane = _build(tmp_path, followers=1)
        _serve(storm, ["doc-0"], rounds=1)
        plane.links[0].down = True
        handle = git.upload("docX", {"kind": "x", "n": 1})
        with pytest.raises(ReplicationQuorumError):
            storm.snapshots.set_head("docX", handle)
        assert git.head("docX") is None
        assert plane.stats["quorum_refusals"] == 1
        with pytest.raises(ReplicationQuorumError):
            storm.checkpoint()
        plane.links[0].down = False
        storm.checkpoint()  # heals: quorum back, flip lands
        _close(storm)

    def test_promote_heads_rolls_crash_window_forward(self, tmp_path):
        """A flip the dead leader shipped but never applied (killed
        between ship and backend flip) rolls FORWARD at promotion; a
        journal can never be older than the backend, so nothing ever
        rolls back."""
        git = GitSnapshotStore(str(tmp_path / "git"))
        node = ReplicaNode(tmp_path / "f0")
        plane = ReplicationPlane([node])
        h1 = git.upload("docX", {"kind": "x", "n": 1})
        plane.ship_head("docX", h1)
        git.set_head("docX", h1)  # applied flip
        h2 = git.upload("docX", {"kind": "x", "n": 2})
        plane.ship_head("docX", h2)  # ...leader dies HERE: no flip
        assert git.head("docX") == h1
        assert promote_heads([node], git) == 1
        assert git.head("docX") == h2
        # Idempotent: a second promotion pass flips nothing.
        assert promote_heads([node], git) == 0

    def test_candidate_choice_prefers_longest_log(self, tmp_path):
        a = ReplicaNode(tmp_path / "a")
        b = ReplicaNode(tmp_path / "b")
        ReplicaLink(b).call(_frame("batch", {"seq": 0, "lens": [2]},
                                   b"xy"))
        assert choose_promotion_candidate([a, b]) is b
        # Equal logs: freshest head journal, then node id.
        ReplicaLink(a).call(_frame("batch", {"seq": 0, "lens": [2]},
                                   b"xy"))
        ReplicaLink(a).call(_frame("head", {"hseq": 1, "key": "k",
                                            "handle": "h"}))
        assert choose_promotion_candidate([a, b]) is a


# -- promotion + fencing -------------------------------------------------------


class TestFailover:

    def test_promotion_reproduces_acked_state_and_rearms(self, tmp_path):
        """Full failover: serve + checkpoint, 'kill' the leader, promote
        the most advanced follower — every converged map row must
        reappear, and the promoted host must itself replicate (fresh
        follower resynced from zero through the plane's own tail
        re-ship)."""
        docs = ["doc-0", "doc-1"]
        git, storm, plane = _build(tmp_path, followers=2)
        clients, cseq = _serve(storm, docs, rounds=2)
        storm.checkpoint()
        _serve(storm, docs, rounds=2, cseq=cseq, clients=clients)
        want = _entries(storm, docs)
        durable = storm._group_wal.durable_len
        _close(storm)  # the "kill": leader gone, followers survive
        nodes = [lk.node for lk in plane.links]
        new_storm, new_plane, report = promote(
            "hostA", nodes, git,
            follower_dirs=[str(tmp_path / "fresh")], num_docs=8)
        assert report["log_len"] == durable
        assert report["blackout_ms"] > 0
        assert report["replayed_ticks"] > 0  # post-checkpoint tail
        assert _entries(new_storm, docs) == want
        # Re-armed: new writes quorum-replicate (surviving follower +
        # the fresh one, resynced from zero at attach).
        assert new_plane.replicated_len == durable
        fresh = [lk for lk in new_plane.links
                 if lk.node.node_id == "fresh"][0]
        assert fresh.node.log_len == durable
        _serve(new_storm, docs, rounds=1, cseq=cseq, clients=None)
        assert new_plane.replicated_len \
            == new_storm._group_wal.durable_len > durable
        _close(new_storm)

    def test_fenced_leader_sheds_refuses_and_never_acks(self, tmp_path):
        """The demoted ex-leader: every frame sheds with a ``moved``
        nack naming the new incarnation, checkpoint() refuses loudly,
        head flips refuse, and the ack watermark stays frozen."""
        _git, storm, plane = _build(tmp_path, followers=1)
        clients, cseq = _serve(storm, ["doc-0"], rounds=1)
        frozen = storm.acked_watermark
        plane.fence(moved_to="hostA")
        shed = []
        storm.submit_frame(
            shed.append,
            {"rid": (99, "doc-0"),
             "docs": [["doc-0", clients["doc-0"], cseq["doc-0"], 1, K]]},
            memoryview(_words([9, 9]).tobytes()))
        storm.flush()
        assert len(shed) == 1
        assert shed[0]["moved_to"] == {"doc-0": "hostA"}
        with pytest.raises(RuntimeError):
            storm.checkpoint()
        with pytest.raises(ReplicationQuorumError):
            plane.ship_head("k", "h")
        assert storm.acked_watermark == frozen
        snap = storm.merge_host.metrics.snapshot()
        assert snap["repl.role_code"] == 3  # demoted
        _close(storm)

    def test_cluster_fail_over_bumps_incarnation_and_flushes_caches(
            self, tmp_path):
        """StormCluster.fail_over: the incarnation stamp bumps DURABLY
        (a rebuilt directory sees it), the old controller is fenced
        toward the label, and historian head caches over the shared
        store are invalidated (promotion flipped backend heads behind
        them)."""
        docs = ["doc-0", "doc-1"]
        git = GitSnapshotStore(str(tmp_path / "git"))
        hist_front = Historian(git, head_ttl_s=1e9)
        old, plane = make_replicated_host(
            "hostA", str(tmp_path / "hostA"), git,
            [str(tmp_path / "f0"), str(tmp_path / "f1")], num_docs=8)
        other = make_cluster_host("hostB", str(tmp_path / "hostB"),
                                  git, num_docs=8)
        cluster = StormCluster({"hostA": old, "hostB": other},
                               hist_front)
        clients, cseq = _serve(old, docs, rounds=2)
        old.checkpoint()
        # A head the historian cached, then — exactly what promotion
        # does — flipped DIRECTLY on the backend behind the cache.
        h1 = git.upload("stale-doc", {"kind": "x", "n": 1})
        git.set_head("stale-doc", h1)
        assert hist_front.head("stale-doc") == h1  # cached, huge TTL
        h2 = git.upload("stale-doc", {"kind": "x", "n": 2})
        git.set_head("stale-doc", h2)
        assert hist_front.head("stale-doc") == h1  # serving stale
        _close(old)
        new_storm, _p, rep = promote(
            "hostA", [lk.node for lk in plane.links], git, num_docs=8)
        inc0 = cluster.directory.incarnation_of("hostA")
        inc = cluster.fail_over("hostA", new_storm,
                                blackout_ms=rep["blackout_ms"])
        assert inc == inc0 + 1
        assert cluster.directory.incarnation_of("hostA") == inc
        assert plane.fenced and plane.moved_to == "hostA"
        assert cluster.hosts["hostA"] is new_storm
        # Head cache flushed by fail_over: the stale entry is gone.
        assert hist_front.head("stale-doc") == h2
        snap = new_storm.merge_host.metrics.snapshot()
        assert snap["repl.last_failover_blackout_ms"] \
            == round(rep["blackout_ms"], 3)
        # Durable: a directory rebuilt over the same store keeps it.
        rebuilt = StormCluster({"hostA": new_storm, "hostB": other},
                               git)
        assert rebuilt.directory.incarnation_of("hostA") == inc
        _close(new_storm)
        _close(other)


# -- ship-failure triage (transient vs permanent) ------------------------------


class _FlakyLink:
    """Raise ``exc`` for the next ``times`` calls, then delegate — a
    transient wire blip (timeout, connection reset) in link clothing."""

    def __init__(self, inner, exc, times=1):
        self.inner, self.exc, self.times = inner, exc, times

    @property
    def node(self):
        return self.inner.node

    def call(self, frame):
        if self.times:
            self.times -= 1
            raise self.exc
        return self.inner.call(frame)


class _VersionRefusingLink:
    """A follower that can NEVER read this stream format — every frame
    nacks ``version``. The permanent incompatibility class."""

    def __init__(self, node_id):
        self.node_id = node_id
        self.log_len = 0
        self.max_hseq = 0
        self.closed = False

    @property
    def node(self):
        return self

    def call(self, frame):
        return {"v": REPLICATION_STREAM_VERSION, "k": "nack", "len": 0,
                "reason": "version"}

    def close(self):
        self.closed = True


class TestShipTriage:
    """_ship_to's failure taxonomy: transient failures retry/resync and
    KEEP the follower; permanent ones (version) drop it without ever
    weakening the quorum arithmetic."""

    def test_transient_linkdown_retries_once_and_acks_same_round(
            self, tmp_path):
        """ReplicationLinkDown (timeout / refused): one immediate
        retransmit — the frame is idempotent — and the round still
        acks. The follower stays in the plane."""
        from fluidframework_tpu.server.replication import (
            ReplicationLinkDown,
        )
        _git, storm, plane = _build(tmp_path, followers=1)
        real = plane.links[0]
        plane.links[0] = _FlakyLink(
            real, ReplicationLinkDown("timed out"), times=1)
        _serve(storm, ["doc-0"], rounds=1)
        assert plane.stats["ship_retries"] == 1
        assert plane.stats["ship_failures"] == 1
        assert plane.stats["followers_dropped"] == 0
        assert len(plane.links) == 1  # follower retained
        # The retransmit delivered: acks advanced with the round.
        assert storm.acked_watermark == storm._group_wal.durable_len > 0
        assert real.node.log_len == storm._group_wal.durable_len
        _close(storm)

    def test_transient_reset_freezes_then_resyncs_on_next_contact(
            self, tmp_path):
        """A non-link-shaped transient (connection reset mid-frame): no
        in-round retry, the watermark freezes, and the NEXT contact
        heals through gap-nack -> resync — the follower is never
        dropped."""
        _git, storm, plane = _build(tmp_path, followers=1)
        real = plane.links[0]
        plane.links[0] = _FlakyLink(
            real, ConnectionResetError("reset by peer"), times=1)
        _serve(storm, ["doc-0"], rounds=1)
        assert plane.stats["ship_failures"] == 1
        assert plane.stats["followers_dropped"] == 0
        assert storm.acked_watermark == 0  # frozen, not lost
        clients, cseq = _serve(storm, ["doc-0"], rounds=1)
        assert plane.stats["resyncs"] >= 1  # gap-nack healed the tail
        assert storm.acked_watermark == storm._group_wal.durable_len
        assert real.node.log_len == storm._group_wal.durable_len
        _close(storm)

    def test_permanent_version_nack_drops_follower_loudly(self, tmp_path):
        """A ``version`` nack is forever: the follower is dropped (and
        closed), ``acks_required`` does NOT shrink with it, so an
        unreachable quorum parks acks and refuses head flips instead of
        silently weakening durability."""
        _git, storm, plane = _build(tmp_path, followers=2,
                                    acks_required=2)
        stub = _VersionRefusingLink(plane.links[1].node.node_id)
        plane.links[1] = stub
        _serve(storm, ["doc-0"], rounds=1)
        assert plane.stats["followers_dropped"] == 1
        assert stub not in plane.links and len(plane.links) == 1
        assert stub.closed
        assert plane.acks_required == 2  # quorum math untouched
        assert storm.acked_watermark == 0  # below quorum: acks park
        assert not plane.quorum_ok
        with pytest.raises(ReplicationQuorumError):
            plane.ship_head("doc-0", "h1")
        _close(storm)
