"""Batched matrix kernel: differential tests against live SharedMatrix
op streams (BASELINE config 4 — matrix.ts:547 processCore,
permutationvector.ts:38 row/col OT, byte-identical converged cells)."""

import random

import pytest

from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops import matrix_kernel as mxk
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_matrix import get_matrix, grid_of


def make_empty_matrix_doc(server, doc_id):
    """Attach empty so EVERY edit rides the sequenced stream (a detached
    matrix ships its initial rows via snapshot, invisible to a replay)."""
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    container.runtime.create_datastore("default").create_channel(
        "grid", SharedMatrix.channel_type)
    container.attach()
    return container


def random_matrix_edit(rng, matrix: SharedMatrix):
    r = rng.random()
    if r < 0.55 and matrix.row_count and matrix.col_count:
        matrix.set_cell(rng.randrange(matrix.row_count),
                        rng.randrange(matrix.col_count),
                        rng.choice(["a", "b", "c", 1, 2.5]))
    elif r < 0.70:
        matrix.insert_rows(rng.randint(0, matrix.row_count),
                           rng.randint(1, 3))
    elif r < 0.85:
        matrix.insert_cols(rng.randint(0, matrix.col_count),
                           rng.randint(1, 3))
    elif r < 0.93 and matrix.row_count:
        pos = rng.randrange(matrix.row_count)
        matrix.remove_rows(pos, min(rng.randint(1, 2),
                                    matrix.row_count - pos))
    elif matrix.col_count:
        pos = rng.randrange(matrix.col_count)
        matrix.remove_cols(pos, min(rng.randint(1, 2),
                                    matrix.col_count - pos))


def replay_through_kernel(server, doc_ids, vec_slots=256, cell_slots=512):
    n = len(doc_ids)
    rows = mxk.HandleAllocator(n)
    cols = mxk.HandleAllocator(n)
    client_slots: dict = {}
    val_ids: dict = {}
    streams = [mxk.encode_matrix_log(server.get_deltas(doc, 0), d, rows,
                                     cols, client_slots, val_ids)
               for d, doc in enumerate(doc_ids)]
    val_rev: list = [None] + [None] * len(val_ids)
    for rep, vid in val_ids.items():
        val_rev[vid] = eval(rep)  # repr of simple literals round-trips
    state = mxk.init_state(n, vec_slots=vec_slots, cell_slots=cell_slots)
    k = 16
    longest = max((len(s) for s in streams), default=0)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        state = mxk.apply_tick(
            state, mxk.make_matrix_op_batch(chunk, n, k))
    margins = mxk.capacity_margin(state)
    assert (margins["rows"] >= 0).all() and (margins["cells"] > 0).all()
    return state, val_rev


@pytest.mark.parametrize("seed", range(4))
def test_matrix_kernel_matches_replicas(seed):
    rng = random.Random(seed)
    n_docs = 2
    server = LocalCollabServer()
    docs = []
    for d in range(n_docs):
        c1 = make_empty_matrix_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(2)]
        docs.append([c1] + others)
        get_matrix(c1).insert_rows(0, 2)
        get_matrix(c1).insert_cols(0, 2)

    for _round in range(4):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 7)):
                random_matrix_edit(rng, get_matrix(
                    containers[rng.randrange(len(containers))]))
            for c in paused:
                c.inbound.resume()

    expected = []
    for containers in docs:
        grids = [grid_of(get_matrix(c)) for c in containers]
        assert all(g == grids[0] for g in grids)
        expected.append(grids[0])

    state, val_rev = replay_through_kernel(
        server, [f"doc{d}" for d in range(n_docs)])
    for d in range(n_docs):
        got = mxk.materialize_grid(state, d, val_rev)
        assert got == expected[d], (seed, d, got, expected[d])


def test_matrix_kernel_concurrent_row_insert_shifts_cells():
    """A cell write whose refSeq predates a concurrent row insert resolves
    in its submitter's frame (the row it addressed, not the shifted one)."""
    server = LocalCollabServer()
    c1 = make_empty_matrix_doc(server, "doc")
    m1 = get_matrix(c1)
    m1.insert_rows(0, 2)
    m1.insert_cols(0, 1)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m2 = get_matrix(c2)

    c1.inbound.pause()
    c2.inbound.pause()
    m1.insert_rows(0, 1)     # shifts rows down for everyone once sequenced
    m2.set_cell(1, 0, "x")   # addressed pre-shift row index 1
    c1.inbound.resume()
    c2.inbound.resume()

    assert grid_of(m1) == grid_of(m2)
    state, val_rev = replay_through_kernel(server, ["doc"])
    assert mxk.materialize_grid(state, 0, val_rev) == grid_of(m1)


def test_matrix_kernel_write_to_removed_row_drops():
    server = LocalCollabServer()
    c1 = make_empty_matrix_doc(server, "doc")
    m1 = get_matrix(c1)
    m1.insert_rows(0, 2)
    m1.insert_cols(0, 1)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m2 = get_matrix(c2)

    c1.inbound.pause()
    c2.inbound.pause()
    m1.remove_rows(0, 1)
    m2.set_cell(0, 0, "dead")  # lands on the removed row's handle
    c1.inbound.resume()
    c2.inbound.resume()

    assert grid_of(m1) == grid_of(m2)
    state, val_rev = replay_through_kernel(server, ["doc"])
    assert mxk.materialize_grid(state, 0, val_rev) == grid_of(m1)


def replay_through_step_kernel(server, doc_ids, vec_slots=256,
                               cell_slots=512, r_max=4):
    """Same replay as replay_through_kernel but through the STEP/RUN
    layout (shared-frame cell runs), chunked so last_vec_seq must carry
    across ticks like the serving host's."""
    n = len(doc_ids)
    rows = mxk.HandleAllocator(n)
    cols = mxk.HandleAllocator(n)
    client_slots: dict = {}
    val_ids: dict = {}
    streams = [mxk.encode_matrix_log(server.get_deltas(doc, 0), d, rows,
                                     cols, client_slots, val_ids)
               for d, doc in enumerate(doc_ids)]
    val_rev: list = [None] + [None] * len(val_ids)
    for rep, vid in val_ids.items():
        val_rev[vid] = eval(rep)
    state = mxk.init_state(n, vec_slots=vec_slots, cell_slots=cell_slots)
    k = 16
    lvs = [0] * n
    longest = max((len(s) for s in streams), default=0)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        steps = mxk.make_matrix_step_batch(chunk, n, r_max=r_max,
                                           last_vec_seq=lvs)
        state = mxk.apply_tick_steps(state, steps)
        for d, ops in enumerate(chunk):
            for op in ops:
                if op["target"] != mxk.MX_CELL:
                    lvs[d] = max(lvs[d], op["seq"])
    return state, val_rev


@pytest.mark.parametrize("seed", range(3))
def test_matrix_step_kernel_matches_flat_and_replicas(seed):
    """The step/run layout must produce the SAME converged state as the
    per-op kernel and the live replicas — including concurrent cells
    with stale refs (paused containers), which must fall back to exact
    single-cell frames."""
    rng = random.Random(100 + seed)
    server = LocalCollabServer()
    c1 = make_empty_matrix_doc(server, "doc")
    others = [Container.load(LocalDocumentService(server, "doc"))
              for _ in range(2)]
    containers = [c1] + others
    get_matrix(c1).insert_rows(0, 2)
    get_matrix(c1).insert_cols(0, 2)
    for _round in range(5):
        paused = [c for c in containers if rng.random() < 0.4]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(6, 12)):
            random_matrix_edit(rng, get_matrix(
                containers[rng.randrange(len(containers))]))
        for c in paused:
            c.inbound.resume()
    grids = [grid_of(get_matrix(c)) for c in containers]
    assert all(g == grids[0] for g in grids)

    flat_state, val_rev = replay_through_kernel(server, ["doc"])
    step_state, val_rev2 = replay_through_step_kernel(server, ["doc"])
    assert mxk.materialize_grid(step_state, 0, val_rev2) == grids[0]
    # Full state equality, not just the materialized view.
    import numpy as np
    import jax
    for a, b in zip(jax.tree.leaves(flat_state),
                    jax.tree.leaves(step_state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", range(3))
def test_cell_run_kernel_matches_per_op(seed):
    """The config-4 fast path: an all-cells tick through apply_cell_run
    materializes the same grid as the per-op scan on the same stream,
    including within-tick duplicate keys (LWW by seq) and writes to
    removed rows (dropped)."""
    rng = random.Random(seed)
    n_docs, grid = 3, 6
    setup = []
    for _ in range(n_docs):
        setup.append([
            dict(target=mxk.MX_ROWS, kind=mtk.MT_INSERT, pos=0,
                 count=grid, handle_base=0, seq=1, ref_seq=0, client=0),
            dict(target=mxk.MX_COLS, kind=mtk.MT_INSERT, pos=0,
                 count=grid, handle_base=0, seq=2, ref_seq=1, client=0),
            # One removed row: cells aimed at it must drop on both paths.
            dict(target=mxk.MX_ROWS, kind=mtk.MT_REMOVE, pos=1, end=2,
                 seq=3, ref_seq=2, client=0),
        ])
    state_a = mxk.init_state(n_docs, vec_slots=16, cell_slots=256)
    state_b = mxk.init_state(n_docs, vec_slots=16, cell_slots=256)
    batch = mxk.make_matrix_op_batch(setup, n_docs, 4)
    state_a = mxk.apply_tick(state_a, batch)
    state_b = mxk.apply_tick(state_b, batch)

    seq = 4
    for _tick in range(3):
        cells_per_doc = []
        for d in range(n_docs):
            cells = []
            for _ in range(rng.randrange(8, 24)):
                cells.append(dict(row=rng.randrange(grid - 1),
                                  col=rng.randrange(grid),
                                  value=rng.randrange(1, 50), seq=seq))
                seq += 1
            cells_per_doc.append(cells)
        ref = seq  # all vector ops acked well below
        run = mxk.make_cell_run_batch(cells_per_doc, n_docs, 24,
                                      [ref] * n_docs, [0] * n_docs)
        state_a = mxk.apply_cell_run(state_a, run)
        per_op = [[dict(target=mxk.MX_CELL, ref_seq=ref, client=0, **c)
                   for c in cells] for cells in cells_per_doc]
        state_b = mxk.apply_tick(
            state_b, mxk.make_matrix_op_batch(per_op, n_docs, 24))

    val_rev = list(range(64))
    for d in range(n_docs):
        grid_a = mxk.materialize_grid(state_a, d, val_rev)
        grid_b = mxk.materialize_grid(state_b, d, val_rev)
        assert grid_a == grid_b, (seed, d)

    # Mixed composition: a per-op tick AFTER cell-run appends must win
    # over the duplicate log entries.
    mixed = [[dict(target=mxk.MX_CELL, row=0, col=0, value=60,
                   seq=seq, ref_seq=seq - 1, client=0)]
             for _ in range(n_docs)]
    state_a = mxk.apply_tick(state_a, mxk.make_matrix_op_batch(
        mixed, n_docs, 1))
    for d in range(n_docs):
        assert mxk.materialize_grid(state_a, d, val_rev)[0][0] == 60
