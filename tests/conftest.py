"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a host-platform device mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Force CPU even when the session has a TPU attached: tests validate
# semantics + sharding on a virtual 8-device host mesh; bench.py uses the
# real chip. NOTE the JAX_PLATFORMS env var alone does NOT stick here (the
# environment pins JAX_PLATFORMS=axon and the plugin wins) — the config
# update below is what takes effect, and it must run before first device use.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
