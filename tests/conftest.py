"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a host-platform device mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Force CPU even when the session has a TPU attached: tests validate
# semantics + sharding on a virtual 8-device host mesh; bench.py uses the
# real chip. NOTE the JAX_PLATFORMS env var alone does NOT stick here (the
# environment pins JAX_PLATFORMS=axon and the plugin wins) — the config
# update below is what takes effect, and it must run before first device use.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Arm the debug-tier truncation guard in ops/mergetree_blocks.to_flat
# (a host-syncing max(count) readback, off on the serving hot path) —
# the suite keeps the tripwire while production stays async. Must be
# set before fluidframework_tpu.ops.mergetree_blocks is imported.
os.environ.setdefault("FFTPU_DEBUG_TO_FLAT", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from fluidframework_tpu.utils import compile_cache  # noqa: E402

# Farms recompile every pool-bucket shape from scratch on a cold run;
# the persistent cache makes re-runs (and the soak/farm tiers) pay XLA
# compilation once per shape per machine instead of once per session.
compile_cache.enable()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reap_cluster_children():
    """Subprocess hygiene: any cluster child spawned through
    tools/launch_cluster during a test is reaped at teardown even when
    the test failed or timed out mid-launch — CI must never accumulate
    orphaned follower/replica processes. Free for the rest of the
    suite (one sys.modules lookup)."""
    import sys

    yield
    mod = sys.modules.get("fluidframework_tpu.tools.launch_cluster")
    if mod is not None:
        mod.reap_all()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture()
def secure_alfred():
    """In-process AlfredServer with auth + tight throttling on a loop
    thread; yields (port, tenant)."""
    import asyncio
    import threading

    from fluidframework_tpu.server.alfred import AlfredServer
    from fluidframework_tpu.server.riddler import TenantManager, Throttler
    from fluidframework_tpu.server.routerlicious import RouterliciousService

    tenants = TenantManager()
    tenant = tenants.create_tenant("acme")
    service = RouterliciousService()
    server = AlfredServer(service, tenants=tenants,
                          throttler=Throttler(rate_per_interval=50,
                                              interval_s=60.0))
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()

    thread = threading.Thread(target=lambda: (
        loop.run_until_complete(run()), loop.run_forever()), daemon=True)
    thread.start()
    assert started.wait(10)
    try:
        yield server.port, tenant
    finally:
        # Best-effort teardown: stop listening, stop the loop. Connection
        # handler tasks die with the daemon thread (py3.12's wait_closed
        # would block on any handler still parked in a read).
        loop.call_soon_threadsafe(
            lambda: server._server is not None and server._server.close())
        loop.call_soon_threadsafe(loop.stop)
        thread.join(10)
