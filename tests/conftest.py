"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a host-platform device mesh (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
