"""Promotion/demotion round-trip property (round-15 satellite): the
``from_block_state`` → shard → serve → gather → ``from_flat`` cycle is
byte-identical to a block-table twin that never left — under TOMBSTONE
pressure and with a CONCURRENT geometry retune (the two seams PR 11/12
added after the original conversion tests were written: the deferred
tombstone zamboni and the packed-flat re-block)."""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops import mergetree_blocks as mtb
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_sharded as mts
from tests.test_mergetree_blocks import gen_stream, occupied_rows


def _tomb_stream(rng: random.Random, n_ops: int) -> list[dict]:
    """gen_stream reshaped toward removes: ~half the ops tombstone, so
    every conversion crosses a table thick with in-window tombstones."""
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(5)
        ref_seq = rng.randrange(max(seq - 4, 0), seq)
        if length > 4 and rng.random() < 0.55:
            start = rng.randrange(length - 2)
            end = start + rng.randint(0, min(3, length - start))
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                            seq=seq, ref_seq=ref_seq, client=client))
            length -= end - start
        else:
            tlen = rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_INSERT, pos=rng.randint(0, length),
                            seq=seq, ref_seq=ref_seq, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


@pytest.mark.parametrize("seed", range(2))
def test_promote_serve_demote_roundtrip_byte_identical(cpu_mesh_devices,
                                                       seed):
    rng = random.Random(500 + seed)
    mesh = mts.make_seg_mesh(cpu_mesh_devices)
    n = len(cpu_mesh_devices)
    slots = 32 * n  # sharded capacity: 32 segment slots per lane
    stream = _tomb_stream(rng, 72)
    half = 40
    k = 8

    def ticks(ops, k):
        for start in range(0, len(ops), k):
            yield mtk.make_merge_op_batch([ops[start:start + k]], 1, k)

    # Twin: the block table serves EVERYTHING, with the serving-path
    # maintenance in between (maybe_rebalance re-decides per tick; a
    # mid-run geometry retune re-blocks through the packed-flat seam).
    twin = mtb.init_state(1, num_blocks=slots // 16, block_slots=16)
    cand = mtb.init_state(1, num_blocks=slots // 16, block_slots=16)
    min_seq = jnp.zeros((1,), jnp.int32)

    def serve_block(state, batch):
        state, ovf = mtb.apply_tick_blocks(state, batch)
        assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)
        return mtb.maybe_rebalance(state, min_seq, k)

    for i, batch in enumerate(ticks(stream[:half], k)):
        twin = serve_block(twin, batch)
        cand = serve_block(cand, batch)
        if i == 2:
            # Concurrent geometry retune (the PR 11 seam): both sides
            # re-block to a coarser Bk through the packed flat form.
            twin = mtb.from_flat(mtb.to_flat(twin, slots=slots),
                                 num_blocks=slots // 32)
            cand = mtb.from_flat(mtb.to_flat(cand, slots=slots),
                                 num_blocks=slots // 32)

    # PROMOTE the candidate: block table -> packed flat (the
    # from_block_state seam) -> segment shards across the mesh lanes.
    flat = mts.from_block_state(cand, slots=slots)
    sharded = mts.shard_merge_state(flat, mesh)
    devices = {s.device for s in sharded.length.addressable_shards}
    assert len(devices) == n  # genuinely lane-placed

    for batch in ticks(stream[half:], k):
        sharded = mts.apply_tick_sharded(sharded, batch, mesh)
        twin = serve_block(twin, batch)

    # DEMOTE: gather -> pack -> from_flat, into the RETUNED geometry.
    gathered = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), sharded)
    packed = mtk.compact(gathered, jnp.full((1,), -1, jnp.int32))
    back = mtb.from_flat(mtb.to_flat(mtb.from_flat(packed,
                                                   num_blocks=slots // 32),
                                     slots=slots),
                         num_blocks=slots // 32)

    # Byte-identity in document order: every occupied slot's full plane
    # tuple (tombstones, overlap words, props included) and the
    # recomputed per-block summaries agree with the never-promoted twin.
    assert occupied_rows(mtb.flat_view(back), 0) == \
        occupied_rows(mtb.flat_view(twin), 0)
    rebuilt = mtb.recompute_summaries(back)
    for f in ("blk_live_len", "blk_max_seq", "blk_tomb", "count"):
        assert np.array_equal(np.asarray(getattr(back, f)),
                              np.asarray(getattr(rebuilt, f))), f
    # Tombstone pressure really was present across the conversions.
    assert int(np.asarray(back.blk_tomb).sum()) > 0

    # Text materializes identically through either lineage.
    pool = mtk.TextPool(1)
    pool.append(0, "x" * 4096)
    assert mtb.materialize(back, pool, 0) == mtb.materialize(twin, pool, 0)
