"""Durable storage: C++ op log, file bus/state store, git-style snapshots,
and full service recovery across a real process boundary.

Reference parity: Kafka segment recovery (services-ordering-*), Mongo
checkpoints (checkpointManager.ts:24), gitrest content-addressed snapshot
storage (gitrest/src/utils.ts:9) — a routerlicious pod restart resumes
from durable state; here the whole service dies with its process and a
fresh process rebuilds it from the same directory.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

import pytest

from fluidframework_tpu.native import OpLog, _PythonOpLog, native_available
from fluidframework_tpu.server.durable_store import (
    DurableMessageBus,
    FileStateStore,
    GitSnapshotStore,
)


class TestOpLog:
    def test_native_toolchain_builds(self):
        assert native_available(), "g++ oplog build failed"

    def test_append_read_reopen(self, tmp_path):
        path = tmp_path / "a.log"
        log = OpLog(path)
        assert log.append(b"one") == 0
        assert log.append(b"two" * 1000) == 1
        log.sync()
        log.close()
        log = OpLog(path)
        assert len(log) == 2
        assert log.read(0) == b"one"
        assert log.read(1) == b"two" * 1000
        log.close()

    def test_torn_tail_truncates(self, tmp_path):
        path = tmp_path / "a.log"
        log = OpLog(path)
        log.append(b"good")
        log.close()
        with open(path, "ab") as f:  # simulate crash mid-append
            f.write(b"\x99\x00\x00\x00partial")
        log = OpLog(path)
        assert len(log) == 1 and log.read(0) == b"good"
        # And appends after recovery land cleanly.
        log.append(b"after")
        log.close()
        log = OpLog(path)
        assert [log.read(i) for i in range(len(log))] == [b"good", b"after"]
        log.close()

    def test_corrupt_crc_truncates(self, tmp_path):
        path = tmp_path / "a.log"
        log = OpLog(path)
        log.append(b"aaaa")
        log.append(b"bbbb")
        log.close()
        data = bytearray(path.read_bytes())
        data[-1] ^= 0xFF  # flip a byte in the last payload
        path.write_bytes(data)
        log = OpLog(path)
        assert len(log) == 1 and log.read(0) == b"aaaa"
        log.close()

    @pytest.mark.skipif(not native_available(), reason="no toolchain")
    def test_python_and_native_formats_interoperate(self, tmp_path):
        path = tmp_path / "x.log"
        py_log = _PythonOpLog(str(path))
        py_log.append(b"from-python")
        py_log.close()
        native = OpLog(path)
        assert native.read(0) == b"from-python"
        native.append(b"from-native")
        native.close()
        py_log = _PythonOpLog(str(path))
        assert [py_log.read(i) for i in range(2)] == [b"from-python",
                                                      b"from-native"]
        py_log.close()


class TestDurableBus:
    def test_produce_survives_reopen_with_offsets(self, tmp_path):
        bus = DurableMessageBus(tmp_path)
        bus.create_topic("t", 2)
        bus.produce("t", "doc-a", {"n": 1})
        bus.produce("t", "doc-a", {"n": 2})
        bus.commit("t", "g", 0, 1)
        bus.commit("t", "g", 1, 1)
        bus.close()

        bus = DurableMessageBus(tmp_path)
        topic = bus.create_topic("t", 2)
        msgs = [m for p in range(2) for m in topic.read(p, 0)]
        assert [m.value for m in msgs] == [{"n": 1}, {"n": 2}]
        parts = {m.offset for m in msgs}
        assert parts == {0, 1}
        committed = [bus.committed("t", "g", p) for p in range(2)]
        assert committed == [1, 1]

    def test_partition_count_pinned_at_creation(self, tmp_path):
        bus = DurableMessageBus(tmp_path)
        bus.create_topic("t", 8)
        for i in range(16):
            bus.produce("t", f"doc-{i}", i)
        bus.close()
        # Reopen asking for a different count: the recorded count wins, so
        # no partition log is orphaned and keys keep their partitions.
        bus = DurableMessageBus(tmp_path)
        topic = bus.create_topic("t", 4)
        assert topic.num_partitions == 8
        values = sorted(m.value for p in range(8) for m in topic.read(p, 0))
        assert values == list(range(16))

    def test_offset_log_compacts(self, tmp_path):
        bus = DurableMessageBus(tmp_path)
        bus.OFFSET_COMPACT_THRESHOLD = 8
        bus.create_topic("t", 1)
        for i in range(200):
            bus.commit("t", "g", 0, i + 1)
        assert len(bus._offset_log) < 50
        bus.close()
        bus = DurableMessageBus(tmp_path)
        assert bus.committed("t", "g", 0) == 200


class TestFileStateStore:
    def test_put_append_reopen_compact(self, tmp_path):
        store = FileStateStore(tmp_path)
        store.put("a", {"x": 1})
        store.append("log", [1, 2])
        store.append("log", [3])
        store.put("a", {"x": 2})
        store.close()

        store = FileStateStore(tmp_path)
        assert store.get("a") == {"x": 2}
        assert store.get("log") == [1, 2, 3]
        store.compact()
        store.close()
        store = FileStateStore(tmp_path)
        assert store.get("a") == {"x": 2}
        assert store.get("log") == [1, 2, 3]
        assert store.keys() == ["a", "log"]
        store.close()


class TestGitSnapshotStore:
    def test_upload_get_head_dedup(self, tmp_path):
        git = GitSnapshotStore(tmp_path)
        snap = {"sequence_number": 5, "tree": {"k": "v" * 100_000}}
        h1 = git.upload("doc", snap)
        h2 = git.upload("doc", snap)
        assert h1 == h2  # content-addressed dedup
        assert git.get("doc", h1) == snap
        assert git.head("doc") is None
        git.set_head("doc", h1)
        assert git.head("doc") == h1
        assert git.get("doc", "0" * 64) is None

    def test_traversal_handles_rejected(self, tmp_path):
        git = GitSnapshotStore(tmp_path)
        outside = tmp_path.parent / "secret.json"
        outside.write_text('{"chunks": []}')
        for evil in ("../secret.json", "../../etc/passwd", "a/b",
                     "A" * 64, "", None, 5):
            assert git.get("doc", evil) is None

    def test_state_store_auto_compacts(self, tmp_path):
        store = FileStateStore(tmp_path)
        store.COMPACT_THRESHOLD = 16
        for i in range(500):
            store.put("clock", i)
        assert len(store._journal) < 100
        store.close()
        store = FileStateStore(tmp_path)
        assert store.get("clock") == 499


_PHASE_A = textwrap.dedent("""
    import json, sys
    from fluidframework_tpu.dds.map import SharedMap
    from fluidframework_tpu.dds.sequence import SharedString
    from fluidframework_tpu.drivers.local_driver import LocalDocumentService
    from fluidframework_tpu.runtime.container import Container
    from fluidframework_tpu.server.alfred import build_default_service

    service = build_default_service(sys.argv[1], merge_host=False)
    c1 = Container.create_detached(LocalDocumentService(service, "doc"))
    ds = c1.runtime.create_datastore("default")
    ds.create_channel("root", SharedMap.channel_type)
    ds.create_channel("text", SharedString.channel_type)
    c1.attach()
    c2 = Container.load(LocalDocumentService(service, "doc"))

    t1 = c1.runtime.get_datastore("default").get_channel("text")
    t2 = c2.runtime.get_datastore("default").get_channel("text")
    m1 = c1.runtime.get_datastore("default").get_channel("root")
    t1.insert_text(0, "hello world")
    t2.insert_text(0, "crash: ")
    m1.set("alive", True)
    t1.remove_text(0, 1)

    print(json.dumps({"text": t1.get_text(),
                      "map": dict(m1.items())}), flush=True)
    # Die WITHOUT any shutdown/close — durability must not depend on it.
""")


class TestServiceRestartAcrossProcess:
    def test_recover_from_dead_process(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-c", _PHASE_A, str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        expected = json.loads(proc.stdout.strip().splitlines()[-1])

        # Fresh process (this one), fresh service object over the same dir.
        from fluidframework_tpu.drivers.local_driver import (
            LocalDocumentService)
        from fluidframework_tpu.runtime.container import Container
        from fluidframework_tpu.server.alfred import build_default_service

        service = build_default_service(str(tmp_path), merge_host=False)
        c3 = Container.load(LocalDocumentService(service, "doc"))
        text = c3.runtime.get_datastore("default").get_channel("text")
        root = c3.runtime.get_datastore("default").get_channel("root")
        assert text.get_text() == expected["text"]
        assert dict(root.items()) == expected["map"]

        # The recovered service still sequences: keep editing + a second
        # client converges.
        text.insert_text(0, "back! ")
        c4 = Container.load(LocalDocumentService(service, "doc"))
        text4 = c4.runtime.get_datastore("default").get_channel("text")
        assert text4.get_text() == "back! " + expected["text"]
        assert text.get_text() == text4.get_text()


class TestTornTailEveryOffset:
    """ISSUE 4 satellite: truncate the WAL at EVERY byte offset inside
    the final frame and prove recovery lands exactly on the last complete
    record — for the raw CRC framing and for _DurablePartition (the
    storm tick WAL gets the same sweep in test_storm_durability)."""

    def test_oplog_every_offset(self, tmp_path):
        path = tmp_path / "w.log"
        log = OpLog(path)
        log.append(b"first-record")
        log.append(b"second-record-" + b"x" * 40)
        log.close()
        full = path.read_bytes()
        first_frame_end = 8 + len(b"first-record")
        probe = tmp_path / "probe.log"
        for cut in range(first_frame_end, len(full)):
            probe.write_bytes(full[:cut])
            log = OpLog(probe)
            assert len(log) == 1, cut
            assert log.read(0) == b"first-record"
            # Appends after recovery land cleanly on the truncated tail.
            log.append(b"post")
            assert log.read(1) == b"post"
            log.close()

    def test_durable_partition_every_offset(self, tmp_path):
        from fluidframework_tpu.server.durable_store import _DurablePartition

        path = tmp_path / "t-0.log"
        part = _DurablePartition(path)
        part.append("doc-a", {"n": 1})
        part.append("doc-a", {"payload": "y" * 64})
        part.close()
        full = path.read_bytes()
        import struct
        (first_len,) = struct.unpack_from("<I", full, 0)
        first_frame_end = 8 + first_len
        probe = tmp_path / "probe-0.log"
        for cut in range(first_frame_end, len(full)):
            probe.write_bytes(full[:cut])
            part = _DurablePartition(probe)
            assert [m.value for m in part.log] == [{"n": 1}], cut
            part.close()


class TestGroupCommitLog:
    def test_watermark_callbacks_and_reopen(self, tmp_path):
        from fluidframework_tpu.server.durable_store import GroupCommitLog

        path = tmp_path / "g.log"
        log = GroupCommitLog(path)
        durable = []
        i0 = log.append(b"alpha", on_durable=durable.append)
        i1 = log.append([b"be", b"ta"], on_durable=durable.append)
        assert (i0, i1) == (0, 1)
        # Reads serve queued records without waiting for the fsync.
        assert log.read(1) == b"beta"
        log.sync()
        assert log.durable_len == 2
        assert sorted(durable) == [0, 1]
        log.close()
        log = GroupCommitLog(path)
        assert len(log) == 2 and log.durable_len == 2
        assert [log.read(i) for i in range(2)] == [b"alpha", b"beta"]
        log.close()

    def test_interoperates_with_plain_oplog(self, tmp_path):
        """The group writer and the sync OpLog share one file format —
        a durability-mode change (or rollback) never orphans a WAL."""
        from fluidframework_tpu.server.durable_store import GroupCommitLog

        path = tmp_path / "g.log"
        log = GroupCommitLog(path)
        log.append(b"from-group")
        log.sync()
        log.close()
        plain = OpLog(path)
        assert plain.read(0) == b"from-group"
        plain.append(b"from-plain")
        plain.close()
        log = GroupCommitLog(path)
        assert [log.read(i) for i in range(len(log))] \
            == [b"from-group", b"from-plain"]
        log.close()

    def test_commit_groups_partition_fsyncs(self, tmp_path):
        """Offsets never claim records the data log could lose: commit()
        syncs the dirty partition before journaling the offset."""
        bus = DurableMessageBus(tmp_path)
        bus.create_topic("t", 1)
        part = bus._topics["t"].partitions[0]
        for i in range(8):
            bus.produce("t", "doc", i)
        assert part.dirty  # appends buffered under one pending fsync
        bus.commit("t", "g", 0, 8)
        assert not part.dirty  # the commit group-synced them
        bus.close()
