"""Golden-snapshot regression: replay checked-in op logs through the full
stack and compare summaries byte-for-byte (replayMultipleFiles.ts:83-92
Compare + Stress modes). These goldens anchor the wire format and summary
format across rounds — a diff here means a format break, not a flake."""

from __future__ import annotations

from pathlib import Path

import pytest

from fluidframework_tpu.tools.replay import verify_corpus, verify_golden

GOLDENS = Path(__file__).parent / "goldens"


@pytest.mark.parametrize("name", sorted(p.name for p in GOLDENS.iterdir()
                                        if p.is_dir()))
def test_golden_compare(name):
    verify_golden(GOLDENS / name)


@pytest.mark.parametrize("name", sorted(p.name for p in GOLDENS.iterdir()
                                        if p.is_dir()))
def test_golden_stress_snapshot_boundaries(name):
    verify_golden(GOLDENS / name, stress=True)


def test_corpus_is_nonempty():
    assert len(verify_corpus(GOLDENS)) >= 5
