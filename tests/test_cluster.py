"""Elastic multi-host serving (parallel/placement.py — the round-16
tentpole): live doc migration between in-process serving hosts over one
shared snapshot store, load-based placement, client redirects, and the
viewer re-home dance. The kill-mid-migration recovery story rides the
chaos harness (tests/test_chaos.py MIGRATION smoke); here the cluster
runs in-process so the phase windows are directly observable."""

from __future__ import annotations

import json

import numpy as np
import pytest

from fluidframework_tpu.parallel.placement import (
    MIGRATION_KILL_POINTS,
    PlacementController,
    StormCluster,
    make_cluster_host,
)
from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.tools.chaos import _cluster_digest


def _words(seed, k=4):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 1], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _build(tmp_path, labels=("hostA", "hostB"), active=None):
    git = GitSnapshotStore(str(tmp_path / "git"))
    hosts = {label: make_cluster_host(label, str(tmp_path / label), git,
                                      num_docs=8)
             for label in labels}
    return git, hosts, StormCluster(hosts, git, active=active)


def _connect(cluster, docs):
    clients = {}
    for d in docs:
        storm = cluster.storm_for(d)
        clients[d] = storm.service.connect(d, lambda m: None).client_id
        storm.service.pump()
    return clients


def _serve_round(cluster, docs, clients, cseq, r, k=4, sink=None):
    for i, d in enumerate(docs):
        storm = cluster.storm_for(d)
        w = _words([r, i], k)
        storm.submit_frame(
            sink or (lambda p: None),
            {"rid": (r, d), "docs": [[d, clients[d], cseq[d], 1, k]]},
            memoryview(w.tobytes()))
        storm.flush()
        cseq[d] += k


def test_migration_under_writes_matches_never_migrated_twin(tmp_path):
    """THE acceptance differential: the same workload served with a
    live mid-run migration must converge byte-identical (merged
    history, map rows, sequencer checkpoints) to a twin cluster that
    never migrated — zero acked-durable ops lost or reordered."""
    docs = [f"doc-{i}" for i in range(3)]

    def play(root, migrate):
        git, hosts, cluster = _build(root)
        clients = _connect(cluster, docs)
        cseq = {d: 1 for d in docs}
        for r in range(2):
            _serve_round(cluster, docs, clients, cseq, r)
        if migrate:
            src = cluster.owner_of(docs[0])
            dst = next(h for h in cluster.labels if h != src)
            blackout = cluster.migrate(docs[0], dst)
            assert blackout > 0
            assert cluster.owner_of(docs[0]) == dst
        for r in range(2, 4):
            _serve_round(cluster, docs, clients, cseq, r)
        return _cluster_digest(cluster, docs)

    migrated = play(tmp_path / "migrated", migrate=True)
    twin = play(tmp_path / "twin", migrate=False)
    assert json.dumps(migrated, sort_keys=True) \
        == json.dumps(twin, sort_keys=True)


def test_moved_and_migrating_nacks_carry_redirect_hints(tmp_path):
    """Client redirect (the PR 8 reconnect path's input): a frame at
    the wrong host sheds ``moved`` with a ``moved_to`` hint; a frame
    DURING the migration blackout sheds ``migrating`` with a retry
    hint; after the flip the old owner redirects to the new one."""
    docs = ["doc-0"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {docs[0]: 1}
    _serve_round(cluster, docs, clients, cseq, 0)
    d = docs[0]
    src = cluster.owner_of(d)
    dst = next(h for h in cluster.labels if h != src)
    nacks = []

    def submit_to(label):
        w = _words([9], 4)
        cluster.hosts[label].submit_frame(
            nacks.append, {"rid": "x", "docs": [[d, clients[d],
                                                 cseq[d], 1, 4]]},
            memoryview(w.tobytes()))

    submit_to(dst)  # wrong host pre-migration
    assert nacks[-1]["error"] == "moved"
    assert nacks[-1]["moved_to"] == {d: src}
    assert nacks[-1]["retryable"] and nacks[-1]["retry_after_s"] > 0

    phases = []

    def on_phase(phase):
        phases.append(phase)
        if phase in ("frozen", "evicted", "hydrated"):
            # Mid-blackout: BOTH hosts shed "migrating" — the doc is
            # between hosts and nothing may sequence on either.
            for label in cluster.labels:
                submit_to(label)
                assert nacks[-1]["error"] == "migrating", (phase, label)
                assert nacks[-1]["retry_after_s"] > 0

    cluster.migrate(d, dst, on_phase=on_phase)
    assert phases == ["frozen", "evicted", "hydrated", "completed"]
    submit_to(src)  # old owner now redirects
    assert nacks[-1]["error"] == "moved"
    assert nacks[-1]["moved_to"] == {d: dst}
    # ...and the new owner serves.
    acks = []
    w = _words([10], 4)
    cluster.hosts[dst].submit_frame(
        acks.append, {"rid": "ok", "docs": [[d, clients[d],
                                             cseq[d], 1, 4]]},
        memoryview(w.tobytes()))
    cluster.hosts[dst].flush()
    assert acks and not acks[-1].get("error")


def test_cold_read_serves_gap_mid_migration_on_both_hosts(tmp_path):
    """Eviction racing a viewer ``viewer_resync`` catch-up (ISSUE 13
    satellite): at EVERY migration phase — mid-evict, post-evict (doc
    cold, no owner), post-hydrate (target volatile) — ``get_deltas``
    must serve the doc's full sequenced gap from the cold-read path on
    whichever host holds the WAL segment, without hydrating."""
    docs = ["doc-0"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {docs[0]: 1}
    for r in range(3):
        _serve_round(cluster, docs, clients, cseq, r)
    d = docs[0]
    src = cluster.owner_of(d)
    dst = next(h for h in cluster.labels if h != src)
    want = [m.sequence_number for m in cluster.get_deltas(d, 0)]
    assert len(want) >= 13  # join + 3 rounds of 4
    seen = {}

    def on_phase(phase):
        if phase == "completed":
            return
        # The reader's gap fetch during the blackout: merged across
        # hosts it must cover the full acked history at every phase.
        got = [m.sequence_number for m in cluster.get_deltas(d, 0)]
        seen[phase] = got
        # The source serves its segment WITHOUT re-hydrating the doc.
        if phase in ("evicted", "hydrated"):
            assert not cluster.hosts[src].residency.is_resident(d)

    cluster.migrate(d, dst, on_phase=on_phase)
    for phase in ("frozen", "evicted", "hydrated"):
        assert seen[phase] == want, phase
    # Post-migration: reads still complete, and the source keeps its
    # pre-migration segment readable (home-stamped cold head).
    assert [m.sequence_number
            for m in cluster.get_deltas(d, 0)] == want
    src_only = [m.sequence_number
                for m in cluster.hosts[src].service.get_deltas(d, 0)]
    assert src_only == want  # all history predates the migration


def test_viewer_room_rehomes_with_moved_hint(tmp_path):
    """Viewer re-home (the PR 13 ``viewer_resync`` dance across
    hosts): migrating a doc drops its source viewer room with a
    ``moved_to`` directive; the viewer catches the gap via get_deltas
    and resumes against the target plane."""
    from fluidframework_tpu.server.broadcaster import ViewerPlane

    docs = ["doc-0"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {docs[0]: 1}
    d = docs[0]
    src = cluster.owner_of(d)
    dst = next(h for h in cluster.labels if h != src)
    src_plane = ViewerPlane(cluster.hosts[src].service)
    dst_plane = ViewerPlane(cluster.hosts[dst].service)
    events = []
    src_plane.join(d, events.append)
    _serve_round(cluster, docs, clients, cseq, 0)
    ticks_before = [e for e in events if isinstance(e, dict)
                    and e.get("event") == "viewer_resync"]
    assert not ticks_before
    cluster.migrate(d, dst)
    directives = [e for e in events if isinstance(e, dict)
                  and e.get("event") == "viewer_resync"]
    assert directives and directives[-1]["moved_to"] == dst
    assert directives[-1]["reason"] == "moved"
    assert cluster.stats["rehomed_viewers"] == 1
    # The re-home dance: gap via merged get_deltas, resume on TARGET.
    gap = cluster.get_deltas(d, directives[-1]["seq"])
    hello = dst_plane.join(d, events.append)
    assert hello["viewer_id"]
    # Live frames flow from the new owner.
    encodes0 = dst_plane.stats["tick_encodes"]
    _serve_round(cluster, docs, clients, cseq, 1)
    assert dst_plane.stats["tick_encodes"] > encodes0


def test_rebalance_2_to_4_hosts_converges(tmp_path):
    """The scale-out driver: genesis on 2 hosts, 2 more activated, the
    placement controller converges the owned-doc spread via live
    migrations — and every doc still serves (values preserved)."""
    labels = ("hostA", "hostB", "hostC", "hostD")
    git, hosts, cluster = _build(tmp_path, labels=labels,
                                 active=["hostA", "hostB"])
    docs = [f"doc-{i}" for i in range(8)]
    clients = _connect(cluster, docs)
    assert {cluster.owner_of(d) for d in docs} <= {"hostA", "hostB"}
    cseq = {d: 1 for d in docs}
    _serve_round(cluster, docs, clients, cseq, 0)
    cluster.activate_host("hostC")
    cluster.activate_host("hostD")
    ctrl = PlacementController(cluster, max_moves_per_round=8)
    report = ctrl.rebalance()
    assert report["converged"], report
    assert report["doc_spread"] <= 1
    assert set(report["docs_per_host"]) == set(labels)
    assert report["moves"] >= 2  # real migrations happened
    # Every doc keeps serving at its (possibly new) owner.
    acks = []
    _serve_round(cluster, docs, clients, cseq, 1, sink=acks.append)
    assert len([a for a in acks if not a.get("error")]) == len(docs)


def test_drain_host_moves_every_doc(tmp_path):
    git, hosts, cluster = _build(tmp_path)
    docs = [f"doc-{i}" for i in range(4)]
    clients = _connect(cluster, docs)
    cseq = {d: 1 for d in docs}
    _serve_round(cluster, docs, clients, cseq, 0)
    hot = max(cluster.labels, key=lambda h: len(cluster.owned(h)))
    assert cluster.owned(hot)
    ctrl = PlacementController(cluster)
    report = ctrl.drain(hot)
    assert report["remaining"] == 0
    assert not cluster.owned(hot)


def test_directory_intent_rolls_forward(tmp_path):
    """A durable MIGRATING intent with no completed flip (the
    post-evict crash window, simulated in-process) rolls FORWARD on
    recover(): the doc ends owned and resident at the target."""
    docs = ["doc-0"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {docs[0]: 1}
    _serve_round(cluster, docs, clients, cseq, 0)
    d = docs[0]
    src = cluster.owner_of(d)
    dst = next(h for h in cluster.labels if h != src)
    # Freeze + evict, then "crash" before the hydrate/flip.
    cluster.directory.freeze(d, src, dst)
    cluster.hosts[src].residency.evict(d, reason="migration")
    code, _ = cluster._route(d, src)
    assert code == "migrating"
    completed = cluster.recover()
    assert completed == [d]
    assert cluster.owner_of(d) == dst
    assert cluster.hosts[dst].residency.is_resident(d)
    acks = []
    _serve_round(cluster, docs, clients, cseq, 1, sink=acks.append)
    assert acks and not acks[-1].get("error")


def test_migration_kill_points_registered():
    assert MIGRATION_KILL_POINTS == (
        "placement.pre_evict", "placement.post_evict",
        "placement.post_hydrate")


def test_storm_stream_moved_nack_records_redirect():
    """The client half: a "moved" nack updates the stream's redirect
    map and fires on_moved WITHOUT arming the send backoff (the right
    response is a different host, not a slower retry here)."""
    from fluidframework_tpu.drivers.network_driver import StormStream

    class _StubService:
        def __init__(self):
            self._handlers = {}
            self._stamp_storm_rx = False

    svc = _StubService()
    moved_events = []
    stream = StormStream(svc, sample_every=0, window=2,
                         on_moved=moved_events.append)
    stream.inflight = 1
    svc._handlers["storm_ack"]({
        "error": "moved", "retry_after_s": 0.5, "rid": 1,
        "moved_to": {"doc-0": "hostB"}, "docs": ["doc-0"]})
    assert stream.moved == {"doc-0": "hostB"}
    assert stream.nacked == 1 and stream.acked == 0
    assert stream.inflight == 0  # the slot freed
    assert stream._backoff_until == 0.0  # no backoff armed
    assert moved_events and moved_events[0]["moved_to"]


def test_viewer_stream_records_rehome_hint():
    from fluidframework_tpu.drivers.network_driver import ViewerStream

    class _StubService:
        def __init__(self):
            self._handlers = {}
            self._token = None
            self._client_key = "ck"

    svc = _StubService()
    stream = ViewerStream(svc)
    svc._handlers["viewer_resync"]({"event": "viewer_resync",
                                    "doc": "d", "seq": 7,
                                    "reason": "moved",
                                    "moved_to": "hostB"})
    assert stream.lagged and stream.moved_to == "hostB"
    assert stream.stats["rehomes"] == 1


def test_round_trip_migration_keeps_full_history_readable(tmp_path):
    """Review regression: a doc migrating h->h' and BACK must re-adopt
    the origin host's own tick index (its ids resolve there), so after
    a further eviction on the original home every host still serves
    its own WAL segment and the merged history stays complete."""
    docs = ["doc-0"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {docs[0]: 1}
    d = docs[0]
    for r in range(2):
        _serve_round(cluster, docs, clients, cseq, r)
    src = cluster.owner_of(d)
    dst = next(h for h in cluster.labels if h != src)
    cluster.migrate(d, dst)
    for r in range(2, 4):
        _serve_round(cluster, docs, clients, cseq, r)
    cluster.migrate(d, src)  # back home
    for r in range(4, 6):
        _serve_round(cluster, docs, clients, cseq, r)
    want = list(range(1, 1 + 1 + 6 * 4))  # join + 6 rounds of 4
    got = [m.sequence_number for m in cluster.get_deltas(d, 0)]
    assert got == want
    # Evict on the original home: its exported index must still cover
    # BOTH of its own segments, and the merged read stays complete.
    cluster.hosts[src].residency.evict(d, reason="idle")
    got_cold = [m.sequence_number for m in cluster.get_deltas(d, 0)]
    assert got_cold == want


def test_activation_survives_cluster_rebuild(tmp_path):
    """Review regression: the activated-host set is durable directory
    state — a rebuilt cluster (restart) resumes the completed 2->4
    scale-out instead of silently shrinking back to genesis."""
    labels = ("hostA", "hostB", "hostC", "hostD")
    git, hosts, cluster = _build(tmp_path, labels=labels,
                                 active=["hostA", "hostB"])
    cluster.activate_host("hostC")
    cluster.activate_host("hostD")
    rebuilt = StormCluster(hosts, git)
    assert sorted(rebuilt.active) == sorted(labels)
    assert sorted(rebuilt.hosts_list()) == sorted(labels)


# -- QoS x placement (round-18 residue): tenant-aware spread -------------------


class _TenantBackend:
    """Deterministic duck-typed backend: three hosts, per-doc tenants,
    static signals — plan() is pure in these."""

    def __init__(self, owned, tenants, loads=None):
        self._owned = {h: list(ds) for h, ds in owned.items()}
        self._tenants = tenants
        self._loads = loads or {}

    def hosts_list(self):
        return sorted(self._owned)

    def owned(self, host):
        return list(self._owned[host])

    def load_signals(self, host):
        tload = {}
        for d in self._owned[host]:
            t = self._tenants.get(d)
            if t is not None:
                tload[t] = tload.get(t, 0) + 1
        return {"docs": len(self._owned[host]), "queue_depth": 0,
                "tick_cost_ms": self._loads.get(host, 0.0),
                "tenant_load": tload}

    def doc_tenant(self, host, doc):
        return self._tenants.get(doc)

    def migrate(self, doc, dst):
        for h, ds in self._owned.items():
            if doc in ds:
                ds.remove(doc)
        self._owned[dst].append(doc)


def test_plan_spreads_hot_tenant_across_hosts():
    """A hot tenant saturating one host spreads to the host where it
    is LIGHTEST: count-tied receivers break ties on that tenant's
    load, and the donor sheds the hot tenant's docs first."""
    tenants = {f"h{i}": "hot" for i in range(6)}
    tenants.update({f"b{i}": "quiet" for i in range(3)})
    backend = _TenantBackend(
        owned={"A": [f"h{i}" for i in range(6)],
               # B and C tie on count; B already carries the hot tenant.
               "B": ["h5x", "b0", "b1"], "C": ["b2", "q0", "q1"]},
        tenants=dict(tenants, h5x="hot", q0="quiet", q1="quiet"))
    ctrl = PlacementController(backend, max_moves_per_round=2)
    plan = ctrl.plan()
    assert plan, "over-count host must shed"
    for doc, src, _dst in plan:
        assert src == "A"
        assert backend.doc_tenant(src, doc) == "hot"
    # First receiver is C (count-tied with B, but 'hot' is lightest
    # there); the next min-count host takes the following move — the
    # hot tenant SPREADS instead of piling onto one receiver.
    assert [dst for _d, _s, dst in plan] == ["C", "B"], plan

    # Tenant-blind backend (no doc_tenant): byte-for-byte the legacy
    # cheapest-first / min-count plan.
    class _Blind(_TenantBackend):
        doc_tenant = None
    blind = _Blind(owned={"A": [f"h{i}" for i in range(6)],
                          "B": ["h5x", "b0", "b1"],
                          "C": ["b2", "q0", "q1"]}, tenants={})
    del _Blind.doc_tenant
    blind_plan = PlacementController(blind, max_moves_per_round=2).plan()
    assert [doc for doc, *_ in blind_plan] == ["h0", "h1"]


def test_cluster_load_signals_carry_tenant_load(tmp_path):
    """StormCluster threads per-tenant doc ownership (observed at the
    storm front door) into the placement signals."""
    git, hosts, cluster = _build(tmp_path)
    docs = ["doc-0", "doc-1"]
    clients = _connect(cluster, docs)
    cseq = {d: 1 for d in docs}
    for i, d in enumerate(docs):
        storm = cluster.storm_for(d)
        storm.submit_frame(
            lambda p: None,
            {"rid": d, "docs": [[d, clients[d], cseq[d], 1, 4]]},
            memoryview(_words([9, i]).tobytes()),
            tenant_id="tn-hot")
        storm.flush()
    total = {}
    for label in cluster.labels:
        sig = cluster.load_signals(label)
        for t, n in sig["tenant_load"].items():
            total[t] = total.get(t, 0) + n
        for d in cluster.owned(label):
            if d in docs:
                assert cluster.doc_tenant(label, d) == "tn-hot"
    assert total == {"tn-hot": 2}


# -- batch drain (round-18 residue): one durable directory write ---------------


def test_batch_drain_uses_two_directory_writes(tmp_path):
    """Draining a host's whole range goes through ONE durable intent
    write + ONE completion write (vs 2 per doc), with every doc served
    on its target afterwards."""
    docs = [f"doc-{i}" for i in range(4)]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {d: 1 for d in docs}
    _serve_round(cluster, docs, clients, cseq, 0)
    hot = max(cluster.labels, key=lambda h: len(cluster.owned(h)))
    n_docs = len(cluster.owned(hot))
    assert n_docs >= 2
    saves = []
    orig = type(cluster.directory)._save

    def counting_save(self):
        saves.append(1)
        return orig(self)

    type(cluster.directory)._save = counting_save
    try:
        report = PlacementController(cluster).drain(hot)
    finally:
        type(cluster.directory)._save = orig
    assert report["remaining"] == 0
    assert report["moves"] == n_docs
    assert report["directory_writes"] == 2
    assert len(saves) == 2, saves
    assert not cluster.directory.migrating
    # Drained docs keep serving at their targets.
    _serve_round(cluster, docs, clients, cseq, 1)
    digest = _cluster_digest(cluster, docs)
    for d in docs:
        assert digest["docs"][d]["map"]


def test_batch_drain_recovery_rolls_each_intent_forward(tmp_path):
    """A batch freeze with no completion (the crash window) is N
    per-doc durable intents published together: recover() rolls every
    one forward individually."""
    docs = ["doc-0", "doc-1", "doc-2"]
    git, hosts, cluster = _build(tmp_path)
    clients = _connect(cluster, docs)
    cseq = {d: 1 for d in docs}
    _serve_round(cluster, docs, clients, cseq, 0)
    hot = max(cluster.labels, key=lambda h: len(cluster.owned(h)))
    dst = next(h for h in cluster.labels if h != hot)
    mine = list(cluster.owned(hot))
    cluster.directory.freeze_many([(d, hot, dst) for d in mine])
    for d in mine:
        assert cluster._route(d, hot)[0] == "migrating"
        if cluster.hosts[hot].residency.is_resident(d):
            cluster.hosts[hot].residency.evict(d, reason="migration")
    completed = cluster.recover()
    assert sorted(completed) == sorted(mine)
    for d in mine:
        assert cluster.owner_of(d) == dst
    _serve_round(cluster, docs, clients, cseq, 1)
