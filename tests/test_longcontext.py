"""Long-context features (SURVEY §5.7): chunked snapshots, lazy edit-log
chunks, bucketed ragged batching (the bucket test lives in
test_merge_host.py)."""

import json
import random

from fluidframework_tpu.dds.mergetree import (
    MergeEngine,
    SNAPSHOT_CHUNK_SEGMENTS,
)
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.dds.tree import (
    EDIT_TAIL_WINDOW,
    EDITS_PER_CHUNK,
    SharedTree,
)
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def _engine_with_segments(n):
    engine = MergeEngine(local_client=None)
    for i in range(n):
        # Alternating clients prevent coalescing; insert at 0 keeps every
        # segment distinct in metadata.
        engine.apply_remote({"type": "insert", "pos": 0, "text": f"s{i},"},
                            i + 1, i, f"c{i % 2}")
    return engine


class TestChunkedMergeSnapshots:
    def test_small_documents_keep_flat_format(self):
        snap = _engine_with_segments(10).snapshot()
        assert "header" not in snap and "extra_chunks" not in snap

    def test_large_snapshot_chunks_and_roundtrips(self):
        n = SNAPSHOT_CHUNK_SEGMENTS + 50
        engine = _engine_with_segments(n)
        snap = engine.snapshot()
        assert snap["header"]["chunk_count"] == 2
        assert len(snap["segments"]) == SNAPSHOT_CHUNK_SEGMENTS
        assert snap["header"]["total_segments"] == \
            len(snap["segments"]) + sum(len(c) for c in
                                        snap["extra_chunks"])
        loaded = MergeEngine.load(snap)
        assert loaded.snapshot() == snap  # canonical: load→snapshot fixed
        # The loaded engine keeps merging correctly.
        loaded.apply_remote({"type": "insert", "pos": 0, "text": "new"},
                            n + 1, n, "c0")
        assert "".join(
            s.content for s in loaded.segments
            if s.removed_seq is None).startswith("new")

    def test_late_joiner_loads_chunked_string(self):
        server = LocalCollabServer()
        c1 = Container.create_detached(LocalDocumentService(server, "doc"))
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("text", SharedString.channel_type)
        c1.attach()
        # A second client that never submits pins the MSN at 0, so every
        # segment stays above the collab window with full identity — the
        # deep-window long-document shape chunking exists for.
        c2 = Container.load(LocalDocumentService(server, "doc"))
        text = ds.get_channel("text")
        rng = random.Random(0)
        for i in range(SNAPSHOT_CHUNK_SEGMENTS + 20):
            text.insert_text(rng.randrange(len(text.get_text()) + 1),
                             f"w{i} ")
        text2 = c2.runtime.get_datastore("default").get_channel("text")
        assert text2.get_text() == text.get_text()
        # Byte-identical chunked summaries from both replicas.
        assert json.dumps(c1.summarize(), sort_keys=True, default=list) == \
            json.dumps(c2.summarize(), sort_keys=True, default=list)
        assert "header" in text2.summarize_core()


def _make_tree(server, doc_id="tree-doc"):
    c = Container.create_detached(LocalDocumentService(server, doc_id))
    ds = c.runtime.create_datastore("default")
    ds.create_channel("tree", SharedTree.channel_type)
    c.attach()
    return c, ds.get_channel("tree")


class TestEditLogChunks:
    def _grow(self, tree, n, start=0):
        for i in range(start, start + n):
            tree.set_payload("root", i) if i % 2 else tree.insert_node(
                {"id": f"n{i}", "definition": "d"},
                {"referenceTrait": {"parent": "root", "label": "kids"},
                 "side": "end"})

    def test_sealing_bounds_resident_log(self):
        server = LocalCollabServer()
        _c, tree = _make_tree(server)
        total = EDITS_PER_CHUNK + EDIT_TAIL_WINDOW + 40
        self._grow(tree, total)
        assert len(tree.log.sequenced) < EDITS_PER_CHUNK + EDIT_TAIL_WINDOW
        assert len(tree._sealed_chunks) >= 1
        # Offloaded to blobs (attached container has storage).
        assert all("blob" in c for c in tree._sealed_chunks)
        # Full history reads back lazily and completely, in order.
        history = list(tree.edit_history())
        assert len(history) == total
        assert len(tree.history_ids()) == total
        assert [r["id"] for r in history] == tree.history_ids()

    def test_chunked_summary_roundtrip_preserves_history(self):
        server = LocalCollabServer()
        c1, tree = _make_tree(server)
        total = EDITS_PER_CHUNK + EDIT_TAIL_WINDOW + 10
        self._grow(tree, total)
        c2 = Container.load(LocalDocumentService(server, "tree-doc"))
        tree2 = c2.runtime.get_datastore("default").get_channel("tree")
        assert tree2.current_view.serialize() == \
            tree.current_view.serialize()
        assert tree2.history_ids() == tree.history_ids()
        assert [r["id"] for r in tree2.edit_history()] == tree.history_ids()
        # And the loaded replica still converges on further edits.
        self._grow(tree, 3, start=total)
        assert tree2.current_view.serialize() == \
            tree.current_view.serialize()

    def test_undo_reaches_into_sealed_chunks(self):
        # Regression: sealing must not break undo for edits still inside
        # the _history snapshot window.
        server = LocalCollabServer()
        _c, tree = _make_tree(server)
        total = EDITS_PER_CHUNK + EDIT_TAIL_WINDOW + 5
        self._grow_inserts(tree, total)
        sealed_ids = [i for c in tree._sealed_chunks for i in c["ids"]]
        target = next(i for i in tree._history if i in sealed_ids)
        assert tree.undo(target) is not None
        assert len(tree.current_view.children("root", "kids")) == total - 1

    @staticmethod
    def _grow_inserts(tree, n):
        for i in range(n):
            tree.insert_node(
                {"id": f"n{i}", "definition": "d"},
                {"referenceTrait": {"parent": "root", "label": "kids"},
                 "side": "end"})

    def test_short_history_summary_format_unchanged(self):
        server = LocalCollabServer()
        _c, tree = _make_tree(server)
        self._grow(tree, 5)
        summary = tree.summarize_core()
        assert set(summary) == {"tree", "edit_ids"}
