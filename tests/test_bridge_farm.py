"""Randomized convergence farm over the C++ bridge front door: concurrent
edits + disconnect/offline-edit/reconnect churn across real sockets — the
reconnectFarm shape (client.reconnectFarm.spec.ts) at the transport level."""

from __future__ import annotations

import random
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.network_driver import NetworkDocumentService
from fluidframework_tpu.native.bridge import _load_library
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.tools.replay import canonical

pytestmark = [
    pytest.mark.soak,
    pytest.mark.slow,
    pytest.mark.skipif(
        _load_library() is None, reason="no C++ toolchain for the bridge"),
]


@pytest.fixture(scope="module")
def bridge_port():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.server.bridge_host",
         "--port", "0", "--no-merge-host"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("READY "), (line, proc.stderr.read())
        yield int(line.split()[1])
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _wait(services, predicate, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        locks = [svc.dispatch_lock for svc in services]
        for lock in locks:
            lock.acquire()
        try:
            if predicate():
                return
        finally:
            for lock in reversed(locks):
                lock.release()
        time.sleep(0.03)
    raise AssertionError("farm did not converge in time")


@pytest.mark.parametrize("seed", range(2))
def test_bridge_reconnect_farm(bridge_port, seed):
    rng = random.Random(seed)
    doc_id = f"farm-{seed}"
    svc0 = NetworkDocumentService("127.0.0.1", bridge_port, doc_id)
    c0 = Container.create_detached(svc0)
    ds = c0.runtime.create_datastore("default")
    ds.create_channel("root", SharedMap.channel_type)
    ds.create_channel("text", SharedString.channel_type)
    with svc0.dispatch_lock:
        c0.attach()

    services = [svc0]
    containers = [c0]
    for _ in range(2):
        svc = NetworkDocumentService("127.0.0.1", bridge_port, doc_id)
        with svc.dispatch_lock:
            containers.append(Container.load(svc))
        services.append(svc)

    def parts(c):
        datastore = c.runtime.get_datastore("default")
        return (datastore.get_channel("root"),
                datastore.get_channel("text"))

    offline: set[int] = set()
    for _round in range(8):
        for i, c in enumerate(containers):
            svc = services[i]
            with svc.dispatch_lock:
                root, text = parts(c)
                r = rng.random()
                if r < 0.15 and i not in offline and i != 0:
                    c.disconnect()
                    offline.add(i)
                elif r < 0.3 and i in offline:
                    c.reconnect()
                    offline.discard(i)
                elif r < 0.7:
                    root.set(f"k{rng.randrange(8)}", rng.randrange(100))
                else:
                    n = len(text.get_text())
                    if n > 6 and rng.random() < 0.4:
                        start = rng.randrange(n - 2)
                        text.remove_text(start,
                                         start + rng.randint(1, 2))
                    else:
                        text.insert_text(rng.randint(0, n),
                                         rng.choice(["ab", "Z", "xyz"]))
    for i in sorted(offline):
        with services[i].dispatch_lock:
            containers[i].reconnect()

    # Summary equality is folded into the locked predicate: checking it
    # after _wait releases the dispatch locks would race a trailing
    # in-flight broadcast applied to only some containers.
    def converged():
        texts = [parts(c)[1].get_text() for c in containers]
        roots = [dict(parts(c)[0].items()) for c in containers]
        seqs = [c.delta_manager.last_processed_seq for c in containers]
        pending = [c.runtime.pending.has_pending for c in containers]
        if not (all(t == texts[0] for t in texts)
                and all(r == roots[0] for r in roots)
                and len(set(seqs)) == 1 and not any(pending)):
            return False
        summaries = [canonical(c.summarize()) for c in containers]
        return summaries[0] == summaries[1] == summaries[2]

    _wait(services, converged)
    for svc in services:
        svc.close()
