"""Differential pin: the run-batched composite apply (within-tick op
parallelism, ops/mergetree_runs.py) against the per-op kernel on the
same sequenced streams."""

import random

import numpy as np
import pytest

from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_runs as mtr


def gen_stream(rng, n_ops, annotate=True):
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(4)
        r = rng.random()
        if length > 24 and r < 0.25:
            start = rng.randrange(length - 8)
            end = start + rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                            seq=seq, ref_seq=seq - 1, client=client))
            length -= end - start
        elif annotate and length > 24 and r < 0.40:
            start = rng.randrange(length - 8)
            ops.append(dict(kind=mtk.MT_ANNOTATE, pos=start,
                            end=start + rng.randint(1, 6), seq=seq,
                            ref_seq=seq - 1, client=client,
                            prop_key=rng.randrange(2),
                            prop_val=rng.randrange(1, 50)))
        else:
            tlen = rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_INSERT,
                            pos=rng.randint(0, length), seq=seq,
                            ref_seq=seq - 1, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def materialize_ids(state, doc):
    """(pool_start, length) of visible segments in order — the converged
    text identity without a host pool."""
    valid = np.asarray(state.valid[doc])
    length = np.asarray(state.length[doc])
    rem = np.asarray(state.rem_seq[doc])
    start = np.asarray(state.pool_start[doc])
    return [(int(start[i]), int(length[i]))
            for i in range(valid.shape[0])
            if valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0]


def props_view(state, doc):
    valid = np.asarray(state.valid[doc])
    rem = np.asarray(state.rem_seq[doc])
    length = np.asarray(state.length[doc])
    start = np.asarray(state.pool_start[doc])
    props = np.asarray(state.prop_val[doc])
    return [(int(start[i]), int(length[i]), tuple(props[i]))
            for i in range(valid.shape[0])
            if valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0]


@pytest.mark.parametrize("seed", range(8))
def test_run_kernel_matches_per_op(seed):
    rng = random.Random(seed)
    n_ops = 48
    stream = gen_stream(rng, n_ops)
    num_slots = 4 * n_ops + 8

    # Per-op reference.
    batch = mtk.make_merge_op_batch([stream], 1, n_ops)
    ref_state = mtk.apply_tick(mtk.init_state(1, num_slots), batch)

    # Run-batched.
    runs = mtr.pack_runs(stream, r_max=8)
    rb = mtr.make_run_batch([runs], 1, len(runs), 8)
    got_state = mtr.apply_tick_runs(mtk.init_state(1, num_slots), rb)

    assert materialize_ids(got_state, 0) == materialize_ids(ref_state, 0)
    assert props_view(got_state, 0) == props_view(ref_state, 0)


class TestPackRunsRejections:
    """The host packer's NEGATIVE paths: ops that would interact inside
    one composite step must NOT pack (ISSUE 2 satellite — the documented
    negative result stays pinned while mergetree_runs remains the
    non-serving reference implementation)."""

    @staticmethod
    def _ins(pos, seq, tlen=3):
        return dict(kind=mtk.MT_INSERT, pos=pos, seq=seq,
                    ref_seq=seq - 1, client=0, pool_start=seq * 10,
                    text_len=tlen)

    @staticmethod
    def _rm(pos, end, seq, ref=None):
        return dict(kind=mtk.MT_REMOVE, pos=pos, end=end, seq=seq,
                    ref_seq=seq - 1 if ref is None else ref, client=0)

    def test_same_position_inserts_never_pack(self):
        # Two inserts at one boundary have a breakTie interaction (the
        # second's placement depends on the first's segment): the second
        # op's position lands inside the first's inserted span.
        runs = mtr.pack_runs([self._ins(0, 1), self._ins(0, 2)])
        assert [len(r) for r in runs] == [1, 1]

    def test_range_touching_in_run_insert_never_packs(self):
        # A remove over a span an in-run insert produced must flush the
        # run (its boundary split would interact with the placement);
        # the two removes that follow are mutually independent in the
        # run-start frame and may pack together — but never with op 1.
        runs = mtr.pack_runs([self._ins(0, 1, tlen=8),
                              self._rm(0, 2, 2), self._rm(3, 5, 3)])
        assert [[op["seq"] for op in r] for r in runs] == [[1], [2, 3]]

    def test_concurrent_ref_never_packs(self):
        # An op whose ref does not cover every prior seq in the run
        # needs its exact per-op frame — one shared frame is unsound.
        # Ops 1+2 are range-independent and pack; op 3 (ref below op
        # 2's seq) must start a fresh run even though its range is far
        # from both.
        ops = [self._ins(0, 1, tlen=4), self._rm(8, 9, 2),
               self._rm(12, 13, 3, ref=1)]
        runs = mtr.pack_runs(ops)
        assert [[op["seq"] for op in r] for r in runs] == [[1, 2], [3]]

    def test_range_spanning_in_run_edit_never_packs(self):
        # A remove whose frame-0 fold shortens its span touches an
        # in-run insert — dependent, must flush.
        runs = mtr.pack_runs([self._ins(4, 1, tlen=3),
                              self._rm(2, 10, 2)])
        assert [len(r) for r in runs] == [1, 1]

    def test_insert_into_removed_seam_never_packs(self):
        # Inserting exactly at a collapsed removed span's seam depends
        # on the remove's tombstones (breakTie skips them).
        runs = mtr.pack_runs([self._rm(2, 4, 1), self._ins(2, 2)])
        assert [len(r) for r in runs] == [1, 1]

    def test_r_max_closes_runs(self):
        ops = [self._ins(8 * i, i + 1, tlen=1) for i in range(6)]
        runs = mtr.pack_runs(ops, r_max=2)
        assert [len(r) for r in runs] == [2, 2, 2]

    def test_rejected_streams_still_apply_exactly(self):
        # The flush boundaries themselves must not change semantics.
        ops = [self._ins(0, 1, tlen=4), self._ins(0, 2, tlen=2),
               self._rm(1, 3, 3), self._ins(3, 4, tlen=1)]
        batch = mtk.make_merge_op_batch([ops], 1, 4)
        ref_state = mtk.apply_tick(mtk.init_state(1, 64), batch)
        runs = mtr.pack_runs(ops, r_max=4)
        rb = mtr.make_run_batch([runs], 1, len(runs), 4)
        got = mtr.apply_tick_runs(mtk.init_state(1, 64), rb)
        assert materialize_ids(got, 0) == materialize_ids(ref_state, 0)


@pytest.mark.parametrize("seed", range(4))
def test_run_kernel_batched_docs(seed):
    rng = random.Random(100 + seed)
    n_docs, n_ops = 4, 32
    streams = [gen_stream(rng, n_ops) for _ in range(n_docs)]
    num_slots = 4 * n_ops + 8

    batch = mtk.make_merge_op_batch(streams, n_docs, n_ops)
    ref_state = mtk.apply_tick(mtk.init_state(n_docs, num_slots), batch)

    runs = [mtr.pack_runs(s, r_max=8) for s in streams]
    t = max(len(r) for r in runs)
    rb = mtr.make_run_batch(runs, n_docs, t, 8)
    got_state = mtr.apply_tick_runs(mtk.init_state(n_docs, num_slots), rb)

    for d in range(n_docs):
        assert materialize_ids(got_state, d) == \
            materialize_ids(ref_state, d), d
        assert props_view(got_state, d) == props_view(ref_state, d), d
