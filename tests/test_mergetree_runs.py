"""Differential pin: the run-batched composite apply (within-tick op
parallelism, ops/mergetree_runs.py) against the per-op kernel on the
same sequenced streams."""

import random

import numpy as np
import pytest

from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_runs as mtr


def gen_stream(rng, n_ops, annotate=True):
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(4)
        r = rng.random()
        if length > 24 and r < 0.25:
            start = rng.randrange(length - 8)
            end = start + rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                            seq=seq, ref_seq=seq - 1, client=client))
            length -= end - start
        elif annotate and length > 24 and r < 0.40:
            start = rng.randrange(length - 8)
            ops.append(dict(kind=mtk.MT_ANNOTATE, pos=start,
                            end=start + rng.randint(1, 6), seq=seq,
                            ref_seq=seq - 1, client=client,
                            prop_key=rng.randrange(2),
                            prop_val=rng.randrange(1, 50)))
        else:
            tlen = rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_INSERT,
                            pos=rng.randint(0, length), seq=seq,
                            ref_seq=seq - 1, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def materialize_ids(state, doc):
    """(pool_start, length) of visible segments in order — the converged
    text identity without a host pool."""
    valid = np.asarray(state.valid[doc])
    length = np.asarray(state.length[doc])
    rem = np.asarray(state.rem_seq[doc])
    start = np.asarray(state.pool_start[doc])
    return [(int(start[i]), int(length[i]))
            for i in range(valid.shape[0])
            if valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0]


def props_view(state, doc):
    valid = np.asarray(state.valid[doc])
    rem = np.asarray(state.rem_seq[doc])
    length = np.asarray(state.length[doc])
    start = np.asarray(state.pool_start[doc])
    props = np.asarray(state.prop_val[doc])
    return [(int(start[i]), int(length[i]), tuple(props[i]))
            for i in range(valid.shape[0])
            if valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0]


@pytest.mark.parametrize("seed", range(8))
def test_run_kernel_matches_per_op(seed):
    rng = random.Random(seed)
    n_ops = 48
    stream = gen_stream(rng, n_ops)
    num_slots = 4 * n_ops + 8

    # Per-op reference.
    batch = mtk.make_merge_op_batch([stream], 1, n_ops)
    ref_state = mtk.apply_tick(mtk.init_state(1, num_slots), batch)

    # Run-batched.
    runs = mtr.pack_runs(stream, r_max=8)
    rb = mtr.make_run_batch([runs], 1, len(runs), 8)
    got_state = mtr.apply_tick_runs(mtk.init_state(1, num_slots), rb)

    assert materialize_ids(got_state, 0) == materialize_ids(ref_state, 0)
    assert props_view(got_state, 0) == props_view(ref_state, 0)


@pytest.mark.parametrize("seed", range(4))
def test_run_kernel_batched_docs(seed):
    rng = random.Random(100 + seed)
    n_docs, n_ops = 4, 32
    streams = [gen_stream(rng, n_ops) for _ in range(n_docs)]
    num_slots = 4 * n_ops + 8

    batch = mtk.make_merge_op_batch(streams, n_docs, n_ops)
    ref_state = mtk.apply_tick(mtk.init_state(n_docs, num_slots), batch)

    runs = [mtr.pack_runs(s, r_max=8) for s in streams]
    t = max(len(r) for r in runs)
    rb = mtr.make_run_batch(runs, n_docs, t, 8)
    got_state = mtr.apply_tick_runs(mtk.init_state(n_docs, num_slots), rb)

    for d in range(n_docs):
        assert materialize_ids(got_state, d) == \
            materialize_ids(ref_state, d), d
        assert props_view(got_state, d) == props_view(ref_state, d), d
