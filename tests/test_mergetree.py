"""Merge-tree engine tests: exact conflict semantics + convergence farms.

Unit tests pin the reference's documented behaviors (mergeTree.ts breakTie /
markRangeRemoved / PropertiesManager); the farms port the reference's
conflictFarm/reconnectFarm stress model (client.conflictFarm.spec.ts:20-57):
random concurrent edits across N clients, replica text equality asserted
after every drain, byte-identical summaries at the end.
"""

import random

import pytest

from fluidframework_tpu.dds.mergetree import Marker, MergeEngine, UNASSIGNED
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


class TestEngineSemantics:
    def test_local_insert_and_text(self):
        e = MergeEngine("a")
        e.insert_local(0, "hello")
        e.insert_local(5, " world")
        e.insert_local(5, ",")
        assert e.get_text() == "hello, world"

    def test_concurrent_same_position_newer_merges_left(self):
        # Observer applies A's insert (seq 1) then B's insert (seq 2), both
        # at position 0 with refSeq 0: later-sequenced lands left (breakTie).
        e = MergeEngine("obs")
        e.apply_remote({"type": "insert", "pos": 0, "text": "AAA"}, 1, 0, "a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "BBB"}, 2, 0, "b")
        assert e.get_text() == "BBBAAA"

    def test_remote_lands_after_local_pending(self):
        e = MergeEngine("a")
        e.insert_local(0, "X")  # pending, will sequence later than B's op
        e.apply_remote({"type": "insert", "pos": 0, "text": "Y"}, 1, 0, "b")
        assert e.get_text() == "XY"
        e.ack(2)
        assert e.get_text() == "XY"
        # The convergent order on a pure observer:
        o = MergeEngine("obs")
        o.apply_remote({"type": "insert", "pos": 0, "text": "Y"}, 1, 0, "b")
        o.apply_remote({"type": "insert", "pos": 0, "text": "X"}, 2, 0, "a")
        assert o.get_text() == "XY"

    def test_foreign_self_excludes_local_unacked_state(self):
        # A VOIDED_LOCAL_ECHO applies an op authored by the local client as
        # remotes do: local pending inserts/removes must not shift positions
        # (no other replica has them).
        e = MergeEngine("a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "base"}, 1, 0, "x")
        e.insert_local(0, "PEND")  # unacked; invisible to every remote
        # Echo of our own voided op: insert at pos 2 of the view WITHOUT
        # the pending text — lands inside "base", not inside "PEND".
        e.apply_remote({"type": "insert", "pos": 2, "text": "_"}, 2, 1, "a",
                       foreign_self=True)
        assert e.get_text() == "PENDba_se"
        # An observer applying the same stream converges (modulo the
        # pending text it cannot see yet).
        o = MergeEngine("obs")
        o.apply_remote({"type": "insert", "pos": 0, "text": "base"}, 1, 0, "x")
        o.apply_remote({"type": "insert", "pos": 2, "text": "_"}, 2, 1, "a")
        assert o.get_text() == "ba_se"

    def test_foreign_self_pending_remove_stays_visible(self):
        e = MergeEngine("a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "abcdef"},
                       1, 0, "x")
        e.remove_local(0, 3)  # pending remove hides "abc" locally only
        # Voided echo removes [1, 3) of the view remotes see ("abcdef"),
        # i.e. "bc" — resolved as if our pending remove did not exist.
        e.apply_remote({"type": "remove", "start": 1, "end": 3}, 2, 1, "a",
                       foreign_self=True)
        o = MergeEngine("obs")
        o.apply_remote({"type": "insert", "pos": 0, "text": "abcdef"},
                       1, 0, "x")
        o.apply_remote({"type": "remove", "start": 1, "end": 3}, 2, 1, "a")
        assert o.get_text() == "adef"
        # After our pending remove acks, both replicas show "def".
        e.ack(3)
        o.apply_remote({"type": "remove", "start": 0, "end": 1}, 3, 1, "a")
        assert e.get_text() == o.get_text() == "def"

    def test_insert_into_concurrently_removed_range(self):
        # B inserts into a range A removed concurrently: the insert survives.
        o = MergeEngine("obs")
        o.apply_remote({"type": "insert", "pos": 0, "text": "abcdef"}, 1, 0, "x")
        o.apply_remote({"type": "remove", "start": 0, "end": 6}, 2, 1, "a")
        o.apply_remote({"type": "insert", "pos": 3, "text": "NEW"}, 3, 1, "b")
        assert o.get_text() == "NEW"

    def test_overlapping_concurrent_removes(self):
        o = MergeEngine("obs")
        o.apply_remote({"type": "insert", "pos": 0, "text": "abcdef"}, 1, 0, "x")
        o.apply_remote({"type": "remove", "start": 1, "end": 5}, 2, 1, "a")
        o.apply_remote({"type": "remove", "start": 0, "end": 6}, 3, 1, "b")
        assert o.get_text() == ""
        # Earliest remove owns removed_seq; b joins the overlap set.
        removed = [s for s in o.segments if s.removed_seq is not None]
        assert any(s.removed_seq == 2 and "b" in s.removed_overlap
                   for s in removed)

    def test_pending_local_remove_overwritten_by_remote(self):
        e = MergeEngine("a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "abc"}, 1, 0, "x")
        e.remove_local(0, 3)  # pending
        e.apply_remote({"type": "remove", "start": 0, "end": 3}, 2, 1, "b")
        e.ack(3)  # our remove acks after b's: removed_seq stays 2
        assert all(s.removed_seq == 2 for s in e.segments
                   if s.removed_seq is not None)
        assert e.get_text() == ""

    def test_annotate_lww_and_pending_shadow(self):
        e = MergeEngine("a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "abc"}, 1, 0, "x")
        e.annotate_local(0, 3, {"bold": True})  # pending shadows the key
        e.apply_remote({"type": "annotate", "start": 0, "end": 3,
                        "props": {"bold": False, "em": True}}, 2, 1, "b")
        # bold shadowed by pending local; em applies.
        assert e.segments[0].props == {"bold": True, "em": True}
        e.ack(3)
        assert e.segments[0].props == {"bold": True, "em": True}

    def test_zamboni_compacts_and_preserves_text(self):
        e = MergeEngine("obs")
        e.apply_remote({"type": "insert", "pos": 0, "text": "aaa"}, 1, 0, "x")
        e.apply_remote({"type": "insert", "pos": 3, "text": "bbb"}, 2, 1, "y")
        e.apply_remote({"type": "remove", "start": 2, "end": 4}, 3, 2, "x")
        assert e.get_text() == "aabb"
        e.update_min_seq(3)
        assert e.get_text() == "aabb"
        # Tombstones dropped; adjacent in-window-exited segments coalesced.
        assert all(s.removed_seq is None for s in e.segments)
        assert len(e.segments) == 1

    def test_markers_occupy_position_space(self):
        e = MergeEngine("a")
        e.insert_local(0, "ab")
        e.insert_local(1, Marker(ref_type="tile", id="m1"))
        assert e.get_text() == "ab"  # text excludes markers
        assert e.local_length() == 3  # but they occupy position space

    def test_zamboni_keeps_segments_with_pending_groups(self):
        # Regression: a pending local annotate references a segment that a
        # remote remove + minSeq advance would collect; regeneration must
        # still find it.
        e = MergeEngine("a")
        e.apply_remote({"type": "insert", "pos": 0, "text": "abc"}, 1, 0, "x")
        e.annotate_local(0, 3, {"bold": True})  # pending
        e.apply_remote({"type": "remove", "start": 0, "end": 3}, 2, 1, "b")
        e.update_min_seq(2)
        group = e.pending_groups[0]
        for seg in group.segments:
            e.get_position_at_local_seq(seg, group.local_seq)  # must not raise
        e.ack(3)
        e.update_min_seq(3)
        assert e.segments == [] or all(s.groups == [] for s in e.segments)

    def test_empty_group_op_advances_seq_on_remotes(self):
        # Regression: an empty regenerated group must advance current_seq on
        # replicas that apply it remotely, or snapshots diverge.
        from fluidframework_tpu.dds.sequence import SharedString
        from fluidframework_tpu.protocol.messages import (
            MessageType, SequencedDocumentMessage)
        s = SharedString("t")
        s.process_core(SequencedDocumentMessage(
            client_id="other", sequence_number=5, minimum_sequence_number=0,
            client_sequence_number=1, reference_sequence_number=0,
            type=MessageType.OPERATION,
            contents={"type": "group", "ops": []}), local=False,
            local_op_metadata=None)
        assert s.engine.current_seq == 5

    def test_snapshot_roundtrip_midwindow(self):
        e = MergeEngine("obs")
        e.apply_remote({"type": "insert", "pos": 0, "text": "abc"}, 1, 0, "x")
        e.apply_remote({"type": "remove", "start": 1, "end": 2}, 2, 1, "y")
        snap = e.snapshot()
        e2 = MergeEngine.load(snap, "z")
        assert e2.get_text() == e.get_text() == "ac"
        assert e2.snapshot() == snap
        # The window op stream continues identically on the loaded replica.
        for engine in (e, e2):
            engine.apply_remote({"type": "insert", "pos": 1, "text": "Z"},
                                3, 1, "w")
        assert e.get_text() == e2.get_text()


# -- farm harness -------------------------------------------------------------


def assert_block_index_exact(engine: MergeEngine) -> None:
    """Recompute every block's settled length / unsettled count from
    scratch and assert equality with the cached stats (VERDICT r5 weak
    #7e): the settled-block index is safety-critical — a drifted
    ``_blk_settled`` silently corrupts every position the walk skips a
    block for — and was previously only exercised implicitly."""
    assert sum(engine._blk_counts) == len(engine.segments)
    assert (len(engine._blk_counts) == len(engine._blk_settled)
            == len(engine._blk_unsettled) == len(engine._blk_text))
    base = 0
    for b, cnt in enumerate(engine._blk_counts):
        settled_len = 0
        unsettled = 0
        for seg in engine.segments[base:base + cnt]:
            if seg.settled_cached:
                # The cached bit must itself be sound: classification is
                # monotone (segments only settle between rebuilds).
                assert engine._is_settled(seg), (b, seg)
                settled_len += engine._settled_contrib(seg)
            else:
                unsettled += 1
        assert engine._blk_settled[b] == settled_len, b
        assert engine._blk_unsettled[b] == unsettled, b
        if engine._blk_unsettled[b] == 0 and engine._blk_text[b] is not None:
            assert engine._blk_text[b] == "".join(
                s.content for s in engine.segments[base:base + cnt]
                if not s.is_marker and s.removed_seq is None), b
        base += cnt


def make_string_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("text", SharedString.channel_type)
    container.attach()
    return container


def get_string(container) -> SharedString:
    return container.runtime.get_datastore("default").get_channel("text")


def random_edit(rng, text_channel):
    length = len(text_channel)
    r = rng.random()
    if r < 0.55 or length == 0:
        pos = rng.randrange(length + 1)
        text_channel.insert_text(pos, rng.choice("abcdefgh") * rng.randrange(1, 4))
    elif r < 0.85:
        start = rng.randrange(length)
        end = min(length, start + rng.randrange(1, 4))
        text_channel.remove_text(start, end)
    else:
        start = rng.randrange(length)
        end = min(length, start + rng.randrange(1, 4))
        text_channel.annotate_range(start, end,
                                    {"k": rng.randrange(3)})


@pytest.mark.parametrize("seed", range(5))
def test_conflict_farm(seed):
    """Port of client.conflictFarm.spec.ts: concurrent random edits with
    paused/interleaved delivery; replicas must match after every drain."""
    rng = random.Random(seed)
    server = LocalCollabServer()
    c1 = make_string_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(3)]
    strings = [get_string(c) for c in containers]

    for _round in range(6):
        # Random subset pauses inbound (edits pile up as pending-vs-remote).
        paused = [c for c in containers if rng.random() < 0.4]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(4, 12)):
            random_edit(rng, strings[rng.randrange(len(strings))])
        for c in paused:
            c.inbound.resume()
        texts = [s.get_text() for s in strings]
        assert all(t == texts[0] for t in texts), (seed, _round, texts)
        for s in strings:
            assert_block_index_exact(s.engine)
    summaries = [c.summarize() for c in containers]
    assert all(s == summaries[0] for s in summaries), seed
    for c in containers:
        assert not c.nacks


@pytest.mark.parametrize("seed", range(3))
def test_reconnect_farm(seed):
    """Port of client.reconnectFarm.spec.ts: random disconnect/reconnect with
    pending-op regeneration; replicas converge after every reconnect wave."""
    rng = random.Random(100 + seed)
    server = LocalCollabServer()
    c1 = make_string_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(2)]
    strings = [get_string(c) for c in containers]

    for _round in range(5):
        offline = [c for c in containers[1:] if rng.random() < 0.5]
        for c in offline:
            c.disconnect()
        for _ in range(rng.randrange(3, 9)):
            random_edit(rng, strings[rng.randrange(len(strings))])
        for c in offline:
            c.reconnect()
        texts = [s.get_text() for s in strings]
        assert all(t == texts[0] for t in texts), (seed, _round, texts)
        for s in strings:
            assert_block_index_exact(s.engine)
    summaries = [c.summarize() for c in containers]
    assert all(s == summaries[0] for s in summaries), seed
