"""Pallas matrix tick kernel: differential tests vs the XLA path.

Mirrors tests/test_mergetree_pallas.py for the composed SharedMatrix
kernel: live SharedMatrix op streams from the real client stack and
synthetic mixed row/col/cell streams must produce identical state through
matrix_pallas.apply_tick_pallas (interpret mode on CPU) and
matrix_kernel.apply_tick.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops import matrix_kernel as mxk
from fluidframework_tpu.ops import matrix_pallas as mxp
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_pallas as mtp
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_matrix import get_matrix, grid_of
from tests.test_matrix_kernel import make_empty_matrix_doc, random_matrix_edit


def _assert_matrix_equal(a: mxk.MatrixState, b: mxk.MatrixState, ctx):
    for axis in ("rows", "cols"):
        for field in mtk.MergeState._fields:
            fa = np.asarray(getattr(getattr(a, axis), field))
            fb = np.asarray(getattr(getattr(b, axis), field))
            assert np.array_equal(fa, fb), (ctx, axis, field)
    for field in ("cell_rh", "cell_ch", "cell_val", "cell_seq",
                  "cell_used", "cell_count"):
        fa = np.asarray(getattr(a, field))
        fb = np.asarray(getattr(b, field))
        assert np.array_equal(fa, fb), (ctx, field)


@pytest.mark.parametrize("seed", range(2))
def test_matrix_pallas_matches_xla_on_live_streams(seed):
    rng = random.Random(seed)
    n_docs = 2
    server = LocalCollabServer()
    docs = []
    for d in range(n_docs):
        c1 = make_empty_matrix_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(2)]
        docs.append([c1] + others)
        get_matrix(c1).insert_rows(0, 2)
        get_matrix(c1).insert_cols(0, 2)

    for _round in range(4):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 7)):
                random_matrix_edit(rng, get_matrix(
                    containers[rng.randrange(len(containers))]))
            for c in paused:
                c.inbound.resume()

    rows = mxk.HandleAllocator(n_docs)
    cols = mxk.HandleAllocator(n_docs)
    client_slots: dict = {}
    val_ids: dict = {}
    streams = [mxk.encode_matrix_log(server.get_deltas(f"doc{d}", 0), d,
                                     rows, cols, client_slots, val_ids)
               for d in range(n_docs)]
    val_rev: list = [None] + [None] * len(val_ids)
    for rep, vid in val_ids.items():
        val_rev[vid] = eval(rep)
    state_x = mxk.init_state(n_docs, vec_slots=128, cell_slots=256)
    state_p = state_x
    k = 16
    longest = max(len(s) for s in streams)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        batch = mxk.make_matrix_op_batch(chunk, n_docs, k)
        state_x = mxk.apply_tick(state_x, batch)
        state_p = mxp.apply_tick_pallas(
            state_p, batch, interpret=mtp.default_interpret())
    _assert_matrix_equal(state_x, state_p, seed)

    # The pallas-produced grid matches the converged replicas.
    for d in range(n_docs):
        expected = grid_of(get_matrix(docs[d][0]))
        got = mxk.materialize_grid(state_p, d, val_rev)
        assert got == expected, (seed, d)


@pytest.mark.parametrize("seed", range(2))
def test_matrix_pallas_matches_xla_on_random_streams(seed):
    from bench import _gen_matrix_stream

    rng = random.Random(100 + seed)
    n_docs = rng.choice([3, 9])
    streams = [_gen_matrix_stream(rng, rng.randrange(10, 40))
               for _ in range(n_docs)]
    k = 8
    state_x = mxk.init_state(n_docs, vec_slots=128, cell_slots=128)
    state_p = state_x
    longest = max(len(s) for s in streams)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        batch = mxk.make_matrix_op_batch(chunk, n_docs, k)
        state_x = mxk.apply_tick(state_x, batch)
        state_p = mxp.apply_tick_pallas(
            state_p, batch, interpret=mtp.default_interpret())
    _assert_matrix_equal(state_x, state_p, seed)


@pytest.mark.parametrize("seed", range(2))
def test_matrix_pallas_step_kernel_matches_xla(seed):
    """The Pallas STEP/RUN kernel (shared-frame cell runs) must be
    bit-identical to the XLA step scan — live concurrent streams with
    stale-ref single-cell runs included."""
    rng = random.Random(300 + seed)
    server = LocalCollabServer()
    c1 = make_empty_matrix_doc(server, "doc")
    others = [Container.load(LocalDocumentService(server, "doc"))
              for _ in range(2)]
    containers = [c1] + others
    get_matrix(c1).insert_rows(0, 2)
    get_matrix(c1).insert_cols(0, 2)
    for _round in range(4):
        paused = [c for c in containers if rng.random() < 0.4]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(5, 10)):
            random_matrix_edit(rng, get_matrix(
                containers[rng.randrange(len(containers))]))
        for c in paused:
            c.inbound.resume()

    rows = mxk.HandleAllocator(1)
    cols = mxk.HandleAllocator(1)
    client_slots: dict = {}
    val_ids: dict = {}
    stream = mxk.encode_matrix_log(server.get_deltas("doc", 0), 0,
                                   rows, cols, client_slots, val_ids)
    state_x = mxk.init_state(1, vec_slots=128, cell_slots=256)
    state_p = state_x
    k = 12
    lvs = [0]
    for start in range(0, len(stream), k):
        chunk = [stream[start:start + k]]
        steps = mxk.make_matrix_step_batch(chunk, 1, r_max=4,
                                           last_vec_seq=lvs)
        state_x = mxk.apply_tick_steps(state_x, steps)
        state_p = mxp.apply_tick_steps_pallas(
            state_p, steps, interpret=mtp.default_interpret())
        for op in chunk[0]:
            if op["target"] != mxk.MX_CELL:
                lvs[0] = max(lvs[0], op["seq"])
    _assert_matrix_equal(state_x, state_p, seed)
    expected = grid_of(get_matrix(containers[0]))
    val_rev: list = [None] + [None] * len(val_ids)
    for rep, vid in val_ids.items():
        val_rev[vid] = eval(rep)
    assert mxk.materialize_grid(state_p, 0, val_rev) == expected


def test_pallas_last_match_composes_with_cell_run_log():
    """A per-op write after cell-run appends must update the NEWEST
    duplicate (Pallas interpret vs XLA vs scalar expectation)."""
    import jax.numpy as jnp
    import numpy as np

    from fluidframework_tpu.ops import matrix_pallas as mxp

    state = mxk.init_state(1, vec_slots=8, cell_slots=32)
    setup = [[dict(target=mxk.MX_ROWS, kind=0, pos=0, count=2,
                   handle_base=0, seq=1, ref_seq=0, client=0),
              dict(target=mxk.MX_COLS, kind=0, pos=0, count=2,
                   handle_base=0, seq=2, ref_seq=1, client=0)]]
    state = mxk.apply_tick(state, mxk.make_matrix_op_batch(setup, 1, 2))
    # Duplicate-key log entries via the cell-run path (seq order 3, 4).
    run = mxk.make_cell_run_batch(
        [[dict(row=0, col=0, value=10, seq=3),
          dict(row=0, col=0, value=20, seq=4)]], 1, 2, [2], [0])
    state = mxk.apply_cell_run(state, run)
    per_op = [[dict(target=mxk.MX_CELL, row=0, col=0, value=30,
                    seq=5, ref_seq=4, client=0)]]
    batch = mxk.make_matrix_op_batch(per_op, 1, 1)
    got_xla = mxk.apply_tick(state, batch)
    got_pallas = mxp.apply_tick_pallas(state, batch, interpret=True)
    val_rev = list(range(64))
    assert mxk.materialize_grid(got_xla, 0, val_rev)[0][0] == 30
    assert mxk.materialize_grid(got_pallas, 0, val_rev)[0][0] == 30
