"""DeltaManager loader-layer tests: live-stream gap recovery via delta
storage, duplicate dedupe, payload-corruption detection, outbound flush
modes, read-only connections and transient signals.

Reference parity model: deltaManager.ts gap fetch (:1298-1360), duplicate
payload check (:1336-1346), FlushMode batching, readonly connections, and
container.ts submitSignal.
"""

import pytest

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.runtime.delta_manager import (
    DataCorruptionError,
    FlushMode,
)
from fluidframework_tpu.server.local_server import LocalCollabServer


class LossyDocumentService(LocalDocumentService):
    """Drops a chosen set of live-broadcast sequence numbers (they stay in
    the server's durable log, as a flaky socket loses frames but the op
    log keeps them)."""

    def __init__(self, server, doc_id, drop_seqs):
        super().__init__(server, doc_id)
        self._drop_seqs = drop_seqs  # live reference: tests mutate it

    def connect(self, handler, on_nack=None, on_signal=None, mode="write"):
        def lossy_handler(messages):
            kept = [m for m in messages
                    if m.sequence_number not in self._drop_seqs]
            if kept:
                handler(kept)
        return super().connect(lossy_handler, on_nack, on_signal, mode)


def make_doc(server, doc_id="doc", service=None):
    service = service or LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("root", SharedMap.channel_type)
    container.attach()
    return container


def root_of(container):
    return container.runtime.get_datastore("default").get_channel("root")


def test_gap_in_live_stream_recovers_from_delta_storage():
    server = LocalCollabServer()
    c1 = make_doc(server)
    # c2's live stream will silently lose two mid-stream messages.
    c2 = Container.load(LossyDocumentService(server, "doc", drop_seqs={5, 6}))
    m1, m2 = root_of(c1), root_of(c2)
    for i in range(8):
        m1.set(f"k{i}", i)
    # The fetch triggered by the first post-gap arrival refilled the hole.
    assert dict(m2.items()) == dict(m1.items())
    assert c1.summarize() == c2.summarize()
    assert c2.delta_manager._parked == {}


def test_tail_drop_recovers_on_next_delivery():
    # Ops at the TAIL of the stream (nothing after them yet) can't be gap-
    # detected until the next message arrives; verify recovery then.
    server = LocalCollabServer()
    c1 = make_doc(server)
    drops = set()
    c2 = Container.load(LossyDocumentService(server, "doc", drop_seqs=drops))
    m1, m2 = root_of(c1), root_of(c2)
    drops.add(c1.last_processed_seq + 1)  # the next sequenced op
    m1.set("a", 1)  # dropped for c2, and no successor yet
    assert dict(m2.items()) == {}
    m1.set("b", 2)  # next seq arrives → hole fetched
    assert dict(m2.items()) == {"a": 1, "b": 2}


def test_duplicate_redelivery_is_dropped():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m1, m2 = root_of(c1), root_of(c2)
    m1.set("x", 1)
    # Redeliver the whole log again (rebroadcast after a server hiccup).
    log = server.get_deltas("doc", 0)
    c2.delta_manager._enqueue_messages(log)
    assert dict(m2.items()) == {"x": 1}
    assert c1.summarize() == c2.summarize()


def test_conflicting_payload_for_same_seq_raises():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    root_of(c1).set("x", 1)
    # Forge two different messages claiming the same far-future seq.
    from dataclasses import replace
    real = server.get_deltas("doc", 0)[-1]
    fake1 = replace(real, sequence_number=99, contents={"v": 1})
    fake2 = replace(real, sequence_number=99, contents={"v": 2})
    c2.delta_manager._accept(fake1)
    with pytest.raises(DataCorruptionError):
        c2.delta_manager._accept(fake2)


def test_manual_flush_batches_outbound():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m1, m2 = root_of(c1), root_of(c2)
    c1.delta_manager.flush_mode = FlushMode.MANUAL
    m1.set("a", 1)
    m1.set("b", 2)
    # Nothing sent yet: remote unchanged, ops held in the open batch.
    assert dict(m2.items()) == {}
    c1.delta_manager.flush()
    assert dict(m2.items()) == {"a": 1, "b": 2}
    assert c1.summarize() == c2.summarize()


def test_readonly_connection_cannot_submit():
    server = LocalCollabServer()
    c1 = make_doc(server)
    reader = Container.load(LocalDocumentService(server, "doc"), mode="read")
    assert reader.delta_manager.readonly
    root_of(c1).set("x", 1)
    assert dict(root_of(reader).items()) == {"x": 1}
    # A read client's local edit stays pending (None client_seq), unsent.
    assert reader.allocate_client_seq() is None


def test_reader_does_not_pin_msn_or_quorum():
    # A read client must not enter the sequencer's MSN calculation: quorum
    # proposals still commit while a reader is connected.
    server = LocalCollabServer()
    c1 = make_doc(server)
    Container.load(LocalDocumentService(server, "doc"), mode="read")
    c1.propose("code", {"pkg": "v2"})
    root_of(c1).set("tick", 1)  # advances c1's refSeq past the proposal
    assert c1.protocol.quorum.get("code") == {"pkg": "v2"}


def test_reconnect_preserves_read_mode():
    server = LocalCollabServer()
    make_doc(server)
    reader = Container.load(LocalDocumentService(server, "doc"), mode="read")
    assert reader.delta_manager.readonly
    reader.reconnect()
    assert reader.delta_manager.readonly
    assert reader.allocate_client_seq() is None


def test_signals_are_transient_broadcast():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    seen1, seen2 = [], []
    c1.on_signal.append(seen1.append)
    c2.on_signal.append(seen2.append)
    c1.submit_signal({"cursor": 7})
    assert seen2 == [{"client_id": c1.client_id, "content": {"cursor": 7}}]
    assert seen1 == seen2  # signals loop back to the sender too
    # Never sequenced: the op log is untouched by signals.
    before = len(server.get_deltas("doc", 0))
    c2.submit_signal("ping")
    assert len(server.get_deltas("doc", 0)) == before
    # Late joiners see no history of signals.
    c3 = Container.load(LocalDocumentService(server, "doc"))
    seen3 = []
    c3.on_signal.append(seen3.append)
    assert seen3 == []


def test_reconnect_mid_gap_stays_consistent():
    server = LocalCollabServer()
    c1 = make_doc(server)
    drops = set()
    c2 = Container.load(LossyDocumentService(server, "doc", drop_seqs=drops))
    m1, m2 = root_of(c1), root_of(c2)
    drops.add(c1.last_processed_seq + 1)
    m1.set("a", 1)      # dropped at c2, unfetchable until next delivery
    drops.clear()
    c2.reconnect()      # catch-up read during connect closes the hole
    assert dict(m2.items()) == {"a": 1}
    m1.set("b", 2)
    m2.set("c", 3)
    assert c1.summarize() == c2.summarize()


class _StubStorage:
    def __init__(self):
        self.log = []

    def get_deltas(self, from_seq, to_seq=None):
        return [m for m in self.log
                if m.sequence_number > from_seq
                and (to_seq is None or m.sequence_number <= to_seq)]


class _StubConnection:
    def __init__(self, client_id):
        self.client_id = client_id

    def close(self):
        pass


class _StubService:
    """Bare DocumentService: a durable log we control + live handlers."""

    def __init__(self):
        self.delta_storage = _StubStorage()
        self.handler = None

    def connect(self, handler, on_nack=None, on_signal=None, mode="write"):
        self.handler = handler
        return _StubConnection("client-1")


def _own_op(seq, payload):
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedDocumentMessage,
    )
    return SequencedDocumentMessage(
        client_id="client-1", sequence_number=seq,
        minimum_sequence_number=0, client_sequence_number=seq,
        reference_sequence_number=seq - 1, type=MessageType.OPERATION,
        contents={"payload": payload}, timestamp=0, data=None)


class TestDurabilityWatermark:
    """Resubmit-on-reconnect against the durability watermark (ISSUE 4):
    own ops echoed from the live stream stay resubmittable until the
    service proves them durable; a reconnect after a server crash that
    lost acked-but-unfsynced ops surfaces exactly the lost ones."""

    def _manager(self, service, lost_sink):
        from fluidframework_tpu.runtime.delta_manager import DeltaManager
        return DeltaManager(service, process_message=lambda m: None,
                            on_lost_ops=lost_sink.extend)

    def test_storage_reads_advance_the_watermark(self):
        service = _StubService()
        lost = []
        dm = self._manager(service, lost)
        service.delta_storage.log = [_own_op(1, "a"), _own_op(2, "b")]
        dm.connect()
        assert dm.last_durable_seq == 2  # journal reads are durable proof
        assert dm._undurable_own == []   # catch-up ops never enter the ring

    def test_live_echoes_stay_resubmittable_until_durable(self):
        service = _StubService()
        lost = []
        dm = self._manager(service, lost)
        dm.connect()
        service.handler([_own_op(1, "a"), _own_op(2, "b"), _own_op(3, "c")])
        assert [m.sequence_number for m in dm._undurable_own] == [1, 2, 3]
        dm.note_durable(2)  # a seq-unit watermark (e.g. a storm ack's per-doc last_seq)
        assert [m.sequence_number for m in dm._undurable_own] == [3]

    def test_reconnect_surfaces_ops_the_crashed_server_lost(self):
        service = _StubService()
        lost = []
        dm = self._manager(service, lost)
        dm.connect()
        service.handler([_own_op(1, "a"), _own_op(2, "b"), _own_op(3, "c")])
        # Server crash: the recovered journal holds only seq 1.
        service.delta_storage.log = [_own_op(1, "a")]
        dm.disconnect()
        dm.connect()
        assert [m.sequence_number for m in lost] == [2, 3]
        assert [m.contents["payload"] for m in lost] == ["b", "c"]
        assert dm._undurable_own == []  # handed to the resubmit hook

    def test_reconnect_with_intact_journal_resubmits_nothing(self):
        service = _StubService()
        lost = []
        dm = self._manager(service, lost)
        dm.connect()
        msgs = [_own_op(1, "a"), _own_op(2, "b")]
        service.handler(msgs)
        service.delta_storage.log = list(msgs)  # journal kept everything
        dm.disconnect()
        dm.connect()
        assert lost == []
        assert dm._undurable_own == []
        assert dm.last_durable_seq == 2

    def test_ring_is_bounded(self):
        from fluidframework_tpu.runtime.delta_manager import DeltaManager
        service = _StubService()
        dm = self._manager(service, [])
        dm.connect()
        n = DeltaManager.RESUBMIT_WINDOW + 10
        service.handler([_own_op(i, i) for i in range(1, n + 1)])
        assert len(dm._undurable_own) == DeltaManager.RESUBMIT_WINDOW
        assert dm._undurable_own[-1].sequence_number == n
