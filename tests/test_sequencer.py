"""Sequencer tests: scalar oracle semantics + batched-kernel differential fuzz.

The fuzz harness mirrors the reference's farm-test philosophy (SURVEY.md §4.2):
random raw-op streams — joins, leaves, ops, dups, gaps, noops, stale refseqs —
through the scalar DocumentSequencer and the batched JAX kernel, asserting
identical tickets and identical end state.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops import opcodes as oc
from fluidframework_tpu.ops import sequencer as seqk
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.server.sequencer import DocumentSequencer, RawOperation


def join(cid, ts=0, can_summarize=True):
    return RawOperation(client_id=None, type=MessageType.CLIENT_JOIN, data=cid,
                        timestamp=ts, can_summarize=can_summarize)


def leave(cid, ts=0):
    return RawOperation(client_id=None, type=MessageType.CLIENT_LEAVE, data=cid,
                        timestamp=ts)


def op(cid, cseq, rseq, mtype=MessageType.OPERATION, ts=0, contents="x"):
    return RawOperation(client_id=cid, type=mtype, client_seq=cseq,
                        ref_seq=rseq, timestamp=ts, contents=contents)


class TestScalarSequencer:
    def test_join_op_leave_flow(self):
        s = DocumentSequencer()
        t1 = s.ticket(join("a"))
        assert (t1.kind, t1.seq) == (oc.OUT_SEQUENCED, 1)
        # Client joined with ref_seq = msn(0): msn stays 0.
        assert t1.msn == 0
        t2 = s.ticket(op("a", 1, 1))
        assert (t2.seq, t2.msn) == (2, 1)
        t3 = s.ticket(leave("a"))
        # No clients left: msn jumps to seq.
        assert (t3.seq, t3.msn) == (3, 3)

    def test_duplicate_is_dropped_gap_is_nacked(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        s.ticket(op("a", 1, 1))
        assert s.ticket(op("a", 1, 1)).kind == oc.OUT_IGNORED
        t = s.ticket(op("a", 5, 1))
        assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_GAP)
        # Client can continue at the expected number.
        assert s.ticket(op("a", 2, 1)).kind == oc.OUT_SEQUENCED

    def test_nonexistent_client_nacked(self):
        s = DocumentSequencer()
        t = s.ticket(op("ghost", 1, 0))
        assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_NONEXISTENT_CLIENT)

    def test_refseq_below_msn_nacks_and_marks_client(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        s.ticket(join("b"))
        s.ticket(op("a", 1, 2))
        s.ticket(op("b", 1, 3))  # msn = min(2,3) = 2
        assert s.minimum_sequence_number == 2
        t = s.ticket(op("a", 2, 1))  # refseq 1 < msn 2
        assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_REFSEQ_BELOW_MSN)
        # Marked client now nacks everything until rejoin.
        t2 = s.ticket(op("a", 3, 4))
        assert (t2.kind, t2.nack_code) == (oc.OUT_NACK, oc.NACK_NONEXISTENT_CLIENT)

    def test_summarize_scope(self):
        s = DocumentSequencer()
        s.ticket(join("a", can_summarize=False))
        t = s.ticket(op("a", 1, 1, mtype=MessageType.SUMMARIZE))
        assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_NO_SUMMARY_SCOPE)

    def test_noop_consolidation(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        s.ticket(op("a", 1, 1))  # seq=2, msn=1, sent → last_sent_msn=1
        # Null-contents noop: never revs, delayed.
        t = s.ticket(op("a", 2, 2, mtype=MessageType.NOOP, contents=None))
        assert (t.kind, t.send, t.seq) == (oc.OUT_SEQUENCED, oc.SEND_LATER, 2)
        # Contentful noop advancing msn: revs + sends.
        t2 = s.ticket(op("a", 3, 2, mtype=MessageType.NOOP, contents="mark"))
        assert (t2.send, t2.seq, t2.msn) == (oc.SEND_IMMEDIATE, 3, 2)
        # Same msn again: delayed, no rev.
        t3 = s.ticket(op("a", 4, 2, mtype=MessageType.NOOP, contents="mark"))
        assert (t3.send, t3.seq) == (oc.SEND_LATER, 3)

    def test_duplicate_join_and_leave_dropped(self):
        s = DocumentSequencer()
        assert s.ticket(join("a")).kind == oc.OUT_SEQUENCED
        assert s.ticket(join("a")).kind == oc.OUT_IGNORED
        assert s.ticket(leave("a")).kind == oc.OUT_SEQUENCED
        assert s.ticket(leave("a")).kind == oc.OUT_IGNORED

    def test_checkpoint_restore(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        s.ticket(op("a", 1, 1))
        cp = s.checkpoint(log_offset=41)
        s2 = DocumentSequencer.restore(cp)
        # Same continuation from both.
        ta, tb = s.ticket(op("a", 2, 2)), s2.ticket(op("a", 2, 2))
        assert (ta.seq, ta.msn) == (tb.seq, tb.msn)
        assert s.checkpoint().clients == s2.checkpoint().clients

    def test_checkpoint_preserves_nack_future(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        s.ticket(RawOperation(client_id=None, type=MessageType.CONTROL,
                              contents={"type": "nackFuture"}))
        s2 = DocumentSequencer.restore(s.checkpoint())
        t = s2.ticket(op("a", 1, 1))
        assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_FUTURE)

    def test_idle_client_detection(self):
        s = DocumentSequencer(client_timeout_ms=100)
        s.ticket(join("a", ts=0))
        s.ticket(join("b", ts=0))
        s.ticket(op("b", 1, 1, ts=500))
        assert s.get_idle_client(now=500) == "a"
        # After the host injects the leave, nobody is idle.
        s.ticket(leave("a", ts=500))
        assert s.get_idle_client(now=500) is None


# -- differential fuzz: scalar vs batched kernel ------------------------------


def random_stream(rng: random.Random, n_ops: int, n_clients: int):
    """Raw op stream over slot-named clients 's0..'; includes every edge."""
    ops = []
    # Track plausible client state to generate a mix of valid + invalid ops.
    next_cseq = {}
    joined = set()
    seq_guess = 0
    for i in range(n_ops):
        r = rng.random()
        cid = f"s{rng.randrange(n_clients)}"
        ts = i
        if r < 0.08:
            ops.append(join(cid, ts=ts, can_summarize=rng.random() < 0.7))
            if cid not in joined:
                joined.add(cid)
                next_cseq[cid] = 1
        elif r < 0.12 and joined:
            target = rng.choice(sorted(joined)) if rng.random() < 0.8 else cid
            ops.append(leave(target, ts=ts))
            joined.discard(target)
        elif r < 0.17:
            # Duplicate or gap clientSeq.
            cseq = next_cseq.get(cid, 1)
            delta = rng.choice([-2, -1, 2, 5])
            ops.append(op(cid, max(cseq + delta, 0), rng.randrange(seq_guess + 1), ts=ts))
        elif r < 0.25:
            # Noop (null or contentful).
            cseq = next_cseq.get(cid, 1)
            contents = None if rng.random() < 0.5 else "probe"
            ops.append(op(cid, cseq, rng.randrange(seq_guess + 1),
                          mtype=MessageType.NOOP, ts=ts, contents=contents))
            if cid in joined:
                next_cseq[cid] = cseq + 1
        elif r < 0.30:
            # Summarize attempt.
            cseq = next_cseq.get(cid, 1)
            ops.append(op(cid, cseq, rng.randrange(seq_guess + 1),
                          mtype=MessageType.SUMMARIZE, ts=ts))
            if cid in joined:
                next_cseq[cid] = cseq + 1
        elif r < 0.33:
            # Client tries to forge a service-only type → NACK_INVALID_TYPE.
            cseq = next_cseq.get(cid, 1)
            forged = rng.choice([MessageType.CONTROL, MessageType.NO_CLIENT,
                                 MessageType.SUMMARY_ACK])
            ops.append(op(cid, cseq, rng.randrange(seq_guess + 1),
                          mtype=forged, ts=ts))
        else:
            # Normal op; refseq sometimes stale, sometimes -1 (REST).
            cseq = next_cseq.get(cid, 1)
            if rng.random() < 0.05:
                rseq = -1
            else:
                rseq = rng.randrange(max(seq_guess, 1))
            ops.append(op(cid, cseq, rseq, ts=ts))
            if cid in joined:
                next_cseq[cid] = cseq + 1
                seq_guess += 1
    return ops


def encode_for_kernel(stream, n_clients):
    """Map the scalar stream to kernel slot encoding (slot i = client 's{i}')."""
    enc = []
    for o in stream:
        if o.client_id is None and o.type in (MessageType.CLIENT_JOIN,
                                              MessageType.CLIENT_LEAVE):
            enc.append(dict(kind=int(o.type), slot=-1, target=int(o.data[1:]),
                            timestamp=o.timestamp,
                            can_summarize=o.can_summarize))
        else:
            enc.append(dict(kind=int(o.type), slot=int(o.client_id[1:]),
                            client_seq=o.client_seq, ref_seq=o.ref_seq,
                            timestamp=o.timestamp,
                            has_contents=o.contents is not None))
    return enc


@pytest.mark.parametrize("seed", range(8))
def test_kernel_matches_scalar_fuzz(seed):
    rng = random.Random(seed)
    n_clients = 6
    n_docs = 4
    k = 32
    n_ticks = 6

    scalars = [DocumentSequencer() for _ in range(n_docs)]
    state = seqk.init_state(n_docs, num_slots=n_clients)

    for _tick in range(n_ticks):
        streams = [random_stream(rng, rng.randrange(k + 1), n_clients)
                   for _ in range(n_docs)]
        # Scalar pass.
        expected = [[s.ticket(o) for o in stream]
                    for s, stream in zip(scalars, streams)]
        # Kernel pass.
        ops = seqk.make_op_batch(
            [encode_for_kernel(st, n_clients) for st in streams], n_docs, k)
        state, out = seqk.process_batch(state, ops)
        out = {f: np.asarray(getattr(out, f)) for f in out._fields}

        for d, tickets in enumerate(expected):
            for i, t in enumerate(tickets):
                got = {f: out[f][d, i] for f in out}
                want_send = t.send if t.kind == oc.OUT_SEQUENCED else oc.SEND_IMMEDIATE
                assert got["kind"] == t.kind, (seed, d, i, t, got)
                if t.kind != oc.OUT_IGNORED:
                    assert got["seq"] == t.seq, (seed, d, i, t, got)
                    assert got["msn"] == t.msn, (seed, d, i, t, got)
                assert got["send"] == want_send, (seed, d, i, t, got)
                assert got["nack_code"] == t.nack_code, (seed, d, i, t, got)

        # End-state equivalence per tick.
        for d, s in enumerate(scalars):
            assert int(state.seq[d]) == s.sequence_number
            assert int(state.msn[d]) == s.minimum_sequence_number
            assert int(state.last_sent_msn[d]) == s.last_sent_msn
            for c in range(n_clients):
                cid = f"s{c}"
                active = bool(state.active[d, c])
                assert active == (cid in s.clients), (seed, d, cid)
                if active:
                    e = s.clients[cid]
                    assert int(state.cseq[d, c]) == e.client_seq
                    assert int(state.cref[d, c]) == e.ref_seq
                    assert bool(state.cnack[d, c]) == e.nack


def test_client_cannot_forge_service_types():
    # Scalar and kernel both NACK a client-submitted CONTROL (e.g. trying to
    # set nack_future) with NACK_INVALID_TYPE, and state is untouched.
    s = DocumentSequencer()
    s.ticket(join("a"))
    t = s.ticket(RawOperation(client_id="a", type=MessageType.CONTROL,
                              client_seq=1, ref_seq=1,
                              contents={"type": "nackFuture"}))
    assert (t.kind, t.nack_code) == (oc.OUT_NACK, oc.NACK_INVALID_TYPE)
    assert not s.nack_future
    assert s.ticket(op("a", 1, 1)).kind == oc.OUT_SEQUENCED

    state = seqk.init_state(1, num_slots=2)
    ops = seqk.make_op_batch([[
        dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0),
        dict(kind=int(MessageType.CONTROL), slot=0, client_seq=1, ref_seq=1,
             is_nack_future=True),
        dict(kind=int(MessageType.OPERATION), slot=0, client_seq=1, ref_seq=1),
    ]], 1, 4)
    state, out = seqk.process_batch(state, ops)
    assert int(out.nack_code[0, 1]) == oc.NACK_INVALID_TYPE
    assert not bool(state.nack_future[0])
    assert int(out.kind[0, 2]) == oc.OUT_SEQUENCED


def test_find_idle_respects_can_evict():
    state = seqk.init_state(1, num_slots=2)
    ops = seqk.make_op_batch([[
        dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0,
             timestamp=0, can_evict=False),
        dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=1,
             timestamp=0),
    ]], 1, 2)
    state, _ = seqk.process_batch(state, ops)
    idle = np.asarray(seqk.find_idle(state, now=10_000, timeout_ms=100))
    assert idle[0].tolist() == [False, True]


def test_checkpoint_preserves_client_timeout():
    s = DocumentSequencer(client_timeout_ms=100)
    s.ticket(join("a", ts=0))
    s2 = DocumentSequencer.restore(s.checkpoint())
    assert s2.get_idle_client(now=500) == "a"


def test_kernel_nack_future_control():
    state = seqk.init_state(1, num_slots=2)
    ops = seqk.make_op_batch([[
        dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0),
        dict(kind=int(MessageType.CONTROL), slot=-1, is_nack_future=True),
        dict(kind=int(MessageType.OPERATION), slot=0, client_seq=1, ref_seq=1),
    ]], 1, 4)
    state, out = seqk.process_batch(state, ops)
    assert int(out.kind[0, 2]) == oc.OUT_NACK
    assert int(out.nack_code[0, 2]) == oc.NACK_FUTURE


def test_find_idle():
    state = seqk.init_state(2, num_slots=3)
    ops = seqk.make_op_batch(
        [[dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0, timestamp=0),
          dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=1, timestamp=900)],
         []], 2, 2)
    state, _ = seqk.process_batch(state, ops)
    idle = np.asarray(seqk.find_idle(state, now=1000, timeout_ms=500))
    assert idle[0].tolist() == [True, False, False]
    assert idle[1].tolist() == [False, False, False]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_storm_tickets_matches_process_batch(seed):
    """The closed-form storm ticket (sequencer.storm_tickets) must be
    bit-identical to the general K-step kernel on the storm frame shape:
    one client/doc, consecutive client_seqs, shared ref/ts — across dup
    prefixes, gaps, inactive/nacked slots, nack_future docs, refSeq<MSN
    and refSeq=-1."""
    import numpy as np

    import fluidframework_tpu.ops.sequencer as seqk

    rng = random.Random(seed)
    b, c, kmax = 32, 8, 12
    state = seqk.init_state(b, c)
    # Randomized prior state: some active clients with varied cseq/cref,
    # some nacked, some docs in nack_future.
    active = np.zeros((b, c), np.bool_)
    cseq = np.zeros((b, c), np.int32)
    cref = np.zeros((b, c), np.int32)
    cnack = np.zeros((b, c), np.bool_)
    seq = np.zeros(b, np.int32)
    msn = np.zeros(b, np.int32)
    nack_future = np.zeros(b, np.bool_)
    for d in range(b):
        seq[d] = rng.randrange(5, 60)
        for s in range(c):
            if rng.random() < 0.7:
                active[d, s] = True
                cseq[d, s] = rng.randrange(0, 20)
                cref[d, s] = rng.randrange(0, seq[d] + 1)
                cnack[d, s] = rng.random() < 0.15
        live = [cref[d, s] for s in range(c) if active[d, s]]
        msn[d] = min(live) if live else seq[d]
        nack_future[d] = rng.random() < 0.1
    state = state._replace(
        seq=jnp.asarray(seq), msn=jnp.asarray(msn),
        last_sent_msn=jnp.asarray(msn),
        nack_future=jnp.asarray(nack_future),
        active=jnp.asarray(active), cseq=jnp.asarray(cseq),
        cref=jnp.asarray(cref), cnack=jnp.asarray(cnack))

    slot = np.zeros(b, np.int32)
    cseq0 = np.zeros(b, np.int32)
    ref = np.zeros(b, np.int32)
    ts = np.full(b, 1234, np.int32)
    counts = np.zeros(b, np.int32)
    for d in range(b):
        s = rng.randrange(c)
        slot[d] = s
        counts[d] = rng.randrange(0, kmax + 1)
        # Exercise dup prefix / exact / gap starts.
        cseq0[d] = cseq[d, s] + 1 + rng.choice([-3, -1, 0, 0, 0, 1, 2])
        ref[d] = rng.choice([-1, max(0, msn[d] - 2), msn[d],
                             int(seq[d])])

    # Reference: the general kernel on the expanded per-op batch.
    ops_per_doc = [
        [dict(kind=int(MessageType.OPERATION), slot=int(slot[d]),
              client_seq=int(cseq0[d] + i), ref_seq=int(ref[d]),
              timestamp=int(ts[d]), has_contents=True)
         for i in range(int(counts[d]))]
        for d in range(b)]
    batch = seqk.make_op_batch(ops_per_doc, b, kmax)
    want_state, want_out = seqk.process_batch(state, batch)

    got_state, dups, n_seq, msn2 = seqk.storm_tickets(
        state, jnp.asarray(slot), jnp.asarray(cseq0), jnp.asarray(ref),
        jnp.asarray(ts), jnp.asarray(counts))

    for field in seqk.SequencerState._fields:
        assert np.array_equal(np.asarray(getattr(got_state, field)),
                              np.asarray(getattr(want_state, field))), field
    # Derived per-op outcomes match the general tickets.
    kind = np.asarray(want_out.kind)
    seq_out = np.asarray(want_out.seq)
    dups = np.asarray(dups)
    n_seq = np.asarray(n_seq)
    for d in range(b):
        want_mask = (kind[d, :counts[d]] == oc.OUT_SEQUENCED)
        got_mask = np.zeros(counts[d], np.bool_)
        got_mask[dups[d]:dups[d] + n_seq[d]] = True
        assert np.array_equal(got_mask, want_mask), (d, kind[d])
        want_seqs = seq_out[d, :counts[d]][want_mask]
        got_seqs = seq[d] + 1 + np.arange(n_seq[d])
        assert np.array_equal(got_seqs, want_seqs), d
    assert np.array_equal(np.asarray(msn2), np.asarray(got_state.msn))


class TestReplayIdempotency:
    """Duplicate-delivery dedup (ISSUE 4 satellite): an already-committed
    op replayed from the WAL, or a client double-submitting after a
    reconnect, must be clientSeq-deduped by the sequencer — never
    re-sequenced. Proven for the scalar oracle AND the device host, and
    across a checkpoint/restore boundary (the restart shape)."""

    def _stream(self):
        return [op("a", 1, 1), op("a", 2, 1), op("a", 3, 2)]

    def test_double_submit_ignored_scalar(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        firsts = [s.ticket(o) for o in self._stream()]
        assert [t.kind for t in firsts] == [oc.OUT_SEQUENCED] * 3
        cp_before = s.checkpoint()
        replays = [s.ticket(o) for o in self._stream()]
        assert [t.kind for t in replays] == [oc.OUT_IGNORED] * 3
        # Dedup changed NOTHING except the clock-free planes.
        assert s.checkpoint() == cp_before
        # The client continues at the expected number afterwards.
        assert s.ticket(op("a", 4, 3)).kind == oc.OUT_SEQUENCED

    def test_double_submit_ignored_kernel_host(self):
        from fluidframework_tpu.server.kernel_host import (
            KernelSequencerHost,
        )

        host = KernelSequencerHost(num_slots=4, initial_capacity=2)
        host.sequence("doc", join("a"))
        for o in self._stream():
            assert host.sequence("doc", o).kind == oc.OUT_SEQUENCED
        cp = host.checkpoint("doc")
        for o in self._stream():  # verbatim resend, no ack seen
            assert host.sequence("doc", o).kind == oc.OUT_IGNORED
        assert host.checkpoint("doc") == cp

    def test_replay_after_restart_is_deduped(self):
        """The WAL-replay shape: restore a checkpoint into a FRESH host,
        then replay ops the checkpoint already covers — all deduped; the
        first genuinely-new op sequences at the next number."""
        from fluidframework_tpu.server.kernel_host import (
            KernelSequencerHost,
        )

        host = KernelSequencerHost(num_slots=4, initial_capacity=2)
        host.sequence("doc", join("a"))
        for o in self._stream():
            host.sequence("doc", o)
        cp = host.checkpoint("doc")

        fresh = KernelSequencerHost(num_slots=4, initial_capacity=2)
        fresh.restore("doc", cp)
        # Replay from below the watermark: already-committed ops drop.
        for o in self._stream():
            assert fresh.sequence("doc", o).kind == oc.OUT_IGNORED
        assert fresh.checkpoint("doc") == cp
        # Post-watermark traffic sequences exactly where the original
        # host would have put it.
        t_fresh = fresh.sequence("doc", op("a", 4, 3))
        t_orig = host.sequence("doc", op("a", 4, 3))
        assert (t_fresh.kind, t_fresh.seq, t_fresh.msn) \
            == (t_orig.kind, t_orig.seq, t_orig.msn)

    def test_replay_after_scalar_restore_is_deduped(self):
        s = DocumentSequencer()
        s.ticket(join("a"))
        for o in self._stream():
            s.ticket(o)
        s2 = DocumentSequencer.restore(s.checkpoint())
        assert [s2.ticket(o).kind for o in self._stream()] \
            == [oc.OUT_IGNORED] * 3
        assert s2.ticket(op("a", 4, 3)).kind == oc.OUT_SEQUENCED
