"""Device-pool snapshot/restore (KernelMergeHost.export_state /
import_state): every device plane — block merge pools, map state, matrix
state — plus the host-side string/slot mappings round-trips through the
content-addressed snapshot store into a FRESH host that serves
identically, including scalar-routed channels and continued ingestion
after the restore."""

import random

import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
)
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost
from tests.test_matrix import get_matrix, make_matrix_doc
from tests.test_merge_host import get_parts, make_doc, random_edit


def seq_msg(seq, channel, contents, client="tail-client", ref=None,
            msn=None):
    return SequencedDocumentMessage(
        client_id=client, sequence_number=seq,
        minimum_sequence_number=msn if msn is not None else max(0, seq - 1),
        client_sequence_number=seq, reference_sequence_number=ref or seq - 1,
        type=MessageType.OPERATION,
        contents={"address": "default",
                  "contents": {"address": channel, "contents": contents}},
        timestamp=seq, data=None)


def build_host_with_traffic(max_client_slots=1024):
    """Text + map + matrix traffic through the real serving stack."""
    host = KernelMergeHost(flush_threshold=16,
                           max_client_slots=max_client_slots)
    server = LocalCollabServer(merge_host=host)
    rng = random.Random(5)
    c1 = make_doc(server, "doc0")
    c2 = Container.load(LocalDocumentService(server, "doc0"))
    for _ in range(12):
        for c in (c1, c2):
            text, root = get_parts(c)
            random_edit(rng, text)
            root.set(f"k{rng.randrange(6)}", rng.randrange(100))
    cm = make_matrix_doc(server, rows=3, cols=3)
    m = get_matrix(cm)
    for r in range(3):
        for col in range(3):
            m.set_cell(r, col, r * 3 + col)
    host.flush()
    return host


def docs_view(host):
    return {
        "text": host.text("doc0", "default", "text"),
        "rich": host.rich_text("doc0", "default", "text"),
        "map": host.map_entries("doc0", "default", "root"),
        "grid": host.matrix_grid("doc", "default", "grid"),
        "summary": host.summarize("doc0"),
    }


def roundtrip(host, tmp_path):
    """Export → the REAL snapshot store (chunked, content-addressed,
    wire-codec serialization) → import into a fresh host."""
    git = GitSnapshotStore(tmp_path / "git")
    handle = git.upload("__pools__", host.export_state())
    loaded = git.get("__pools__", handle)
    host2 = KernelMergeHost(flush_threshold=16,
                            max_client_slots=host.max_client_slots)
    host2.import_state(loaded)
    return host2


def test_export_import_reproduces_every_plane(tmp_path):
    host = build_host_with_traffic()
    host2 = roundtrip(host, tmp_path)
    assert docs_view(host2) == docs_view(host)


def test_restored_host_keeps_serving_identically(tmp_path):
    host = build_host_with_traffic()
    host2 = roundtrip(host, tmp_path)
    # The same sequenced tail into both hosts → identical convergence
    # (slot mappings, interning and seq frontiers all survived).
    base = host.summarize("doc0")["sequence_number"]
    tail = [
        seq_msg(base + 1, "text", {"type": "insert", "pos": 0,
                                   "text": "post-restore "}),
        seq_msg(base + 2, "root", {"type": "set", "key": "fresh",
                                   "value": 41}),
        seq_msg(base + 3, "text", {"type": "annotate", "start": 0,
                                   "end": 4, "props": {"b": True}}),
    ]
    for h in (host, host2):
        for m in tail:
            h.ingest("doc0", m)
        h.flush()
    assert docs_view(host2) == docs_view(host)
    assert host2.text("doc0", "default", "text").startswith("post-restore ")


def test_scalar_routed_channel_roundtrips(tmp_path):
    """A channel overflow-routed to the scalar engine exports its engine
    and keeps serving scalar-side after import."""
    host = KernelMergeHost(flush_threshold=8, max_client_slots=32)
    # More distinct writers than the client-slot ceiling routes the
    # channel off the device mid-stream.
    # msn pinned at 0: every writer stays in the collab window, so the
    # zamboni cannot coalesce the writer set back under the ceiling
    # (which would legitimately readmit the channel to the device).
    for seq in range(1, 41):
        host.ingest("sdoc", seq_msg(
            seq, "text", {"type": "insert", "pos": 0, "text": f"w{seq} "},
            client=f"writer-{seq}", msn=0))
    host.flush()
    assert host.stats["overflow_routed"] > 0
    key = [k for k in host._merge_rows if k.channel == "text"][0]
    assert host._merge_rows[key].scalar is not None

    git = GitSnapshotStore(tmp_path / "git")
    handle = git.upload("__pools__", host.export_state())
    host2 = KernelMergeHost(flush_threshold=8, max_client_slots=32)
    host2.import_state(git.get("__pools__", handle))
    assert host2._merge_rows[key].scalar is not None
    assert (host2.text("sdoc", "default", "text")
            == host.text("sdoc", "default", "text"))
    assert (host2.rich_text("sdoc", "default", "text")
            == host.rich_text("sdoc", "default", "text"))
    # Scalar serving continues identically after the restore.
    tail = seq_msg(41, "text", {"type": "remove", "start": 0, "end": 3},
                   client="writer-41", msn=0)
    for h in (host, host2):
        h.ingest("sdoc", tail)
        h.flush()
    assert (host2.text("sdoc", "default", "text")
            == host.text("sdoc", "default", "text"))


def test_import_requires_fresh_host(tmp_path):
    host = build_host_with_traffic()
    snap = host.export_state()
    with pytest.raises(AssertionError, match="fresh host"):
        host.import_state(snap)


def _assert_pools_identical(a, b):
    """Every merge pool byte-identical between two hosts: geometry AND
    all device planes (the replay-determinism comparison surface)."""
    import numpy as np
    assert sorted(a._merge_pools) == sorted(b._merge_pools)
    for slots, pa in a._merge_pools.items():
        pb = b._merge_pools[slots]
        assert type(pa) is type(pb), slots
        if hasattr(pa, "nb"):
            assert (pa.nb, pa.bk) == (pb.nb, pb.bk), slots
        for f in type(pa.state)._fields:
            assert np.array_equal(np.asarray(getattr(pa.state, f)),
                                  np.asarray(getattr(pb.state, f))), \
                (slots, f)


def test_geometry_retune_snapshot_replay_determinism(tmp_path):
    """The round-11 replay/restore determinism bar: a geometry retune +
    incremental-rebalance sequence survives export_state/import_state
    byte-identically, a restore of the PRE-retune snapshot that re-runs
    the same retune re-decides the same layout byte-for-byte, and the
    same sequenced tail (the WAL-tail replay analog) converges all
    replicas identically on the retuned geometry."""
    import random as _random

    from fluidframework_tpu.server.local_server import LocalCollabServer

    host = KernelMergeHost(flush_threshold=8)
    server = LocalCollabServer(merge_host=host)
    c = make_doc(server, "doc0")
    # A second writer that never submits pins the MSN, so the zamboni
    # cannot coalesce the head-insert run — the table genuinely grows
    # and the head-concentrated stream arms the rebalance trigger.
    Container.load(LocalDocumentService(server, "doc0"))
    text, _root = get_parts(c)
    for i in range(300):
        text.insert_text(0, f"edit{i} ")
    host.flush()
    assert host.stats["rebalances"] > 0  # incremental ladder exercised
    git = GitSnapshotStore(tmp_path / "git")
    pre = git.upload("__pools__", host.export_state())
    # The stream IS head-concentrated (every insert at pos 0); pin the
    # concentration estimate so every block pool — including the one the
    # doc migrated into — re-blocks, and the decision is deterministic
    # for the pre-retune-restore replica below.
    retuned = host.autotune_block_geometry(min_observations=1,
                                           fire_threshold=0.0,
                                           head_fraction=1.0)
    assert retuned, "head-concentrated stream never tripped the autotune"
    assert host.stats["geometry_retunes"] >= 1

    # (a) Replay re-decides identically: restore the PRE-retune snapshot
    # and apply the same retune decisions — byte-identical pools
    # (pool.retune is a pure function of (state, block_slots)).
    host2 = KernelMergeHost(flush_threshold=8)
    host2.import_state(git.get("__pools__", pre))
    for slots, (_nb, bk) in retuned.items():
        host2._merge_pools[slots].retune(bk)
    _assert_pools_identical(host, host2)

    # (b) The retuned geometry itself survives the snapshot seam (the
    # "block_geometry" stamp re-blocks the fresh pool before planes
    # load).
    post = git.upload("__pools__", host.export_state())
    host3 = KernelMergeHost(flush_threshold=8)
    host3.import_state(git.get("__pools__", post))
    for slots, (nb, bk) in retuned.items():
        p = host3._merge_pools[slots]
        assert (p.nb, p.bk) == (nb, bk), slots
    _assert_pools_identical(host, host3)

    # (c) The same sequenced tail through original and both restores
    # converges byte-identically — the tail keeps hammering the head so
    # the incremental rebalance re-fires on the retuned geometry.
    base = host.summarize("doc0")["sequence_number"]
    rng = _random.Random(11)
    tail = [seq_msg(base + 1 + i, "text",
                    {"type": "insert", "pos": 0,
                     "text": f"t{rng.randrange(100)} "})
            for i in range(24)]
    for h in (host, host2, host3):
        for m in tail:
            h.ingest("doc0", m)
        h.flush()
    assert (host2.text("doc0", "default", "text")
            == host3.text("doc0", "default", "text")
            == host.text("doc0", "default", "text"))
    _assert_pools_identical(host, host2)
    _assert_pools_identical(host, host3)


def test_tree_channels_are_flagged_for_log_replay(tmp_path):
    """Tree channels are not snapshotted (they rebuild from the durable
    op-log replay); export records their keys so callers know."""
    from fluidframework_tpu.dds.tree_core import ROOT_ID
    from tests.test_tree_host import get_tree, make_tree_doc, node

    host = KernelMergeHost(flush_threshold=4)
    server = LocalCollabServer(merge_host=host)
    c = make_tree_doc(server, "tdoc")
    get_tree(c).insert_node(
        node("n1"), {"referenceTrait": {"parent": ROOT_ID,
                                        "label": "children"},
                     "side": "end"})
    host.flush()
    snap = host.export_state()
    assert ["tdoc", "default", "tree"] in snap["tree_keys"]
