"""Request routing chain + dependency synthesizer
(packages/framework/request-handler, packages/framework/synthesize)."""

import pytest

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.framework import (
    ContainerRuntimeFactoryWithDefaultDataStore,
    DataObjectFactory,
    DependencyContainer,
    DependencyError,
    RuntimeRequestRouter,
    datastore_request_handler,
    default_route_handler,
)
from fluidframework_tpu.framework.data_object import DataObject
from fluidframework_tpu.server.local_server import LocalCollabServer


class _Note(DataObject):
    def initializing_first_time(self, props=None) -> None:
        counter = self.runtime.create_channel(
            "count", SharedCounter.channel_type)
        self.root.set("count", counter.handle)


def _make_doc():
    factory = ContainerRuntimeFactoryWithDefaultDataStore(
        DataObjectFactory("note", _Note))
    server = LocalCollabServer()
    container, obj = factory.create_document(
        LocalDocumentService(server, "doc"))
    container.attach()
    return factory, container, obj


class TestRequestRouting:
    def test_root_resolves_typed_default_object(self):
        factory, container, _ = _make_doc()
        response = factory.request(container, "/")
        assert response.ok
        assert isinstance(response.value, _Note)

    def test_datastore_and_channel_paths(self):
        factory, container, _ = _make_doc()
        by_id = factory.request(container, "/default")
        assert by_id.ok and isinstance(by_id.value, _Note)
        channel = factory.request(container, "/default/root")
        assert channel.ok
        from fluidframework_tpu.dds.directory import SharedDirectory
        assert isinstance(channel.value, SharedDirectory)

    def test_unknown_route_404(self):
        factory, container, _ = _make_doc()
        assert factory.request(container, "/nope").status == 404
        assert factory.request(container, "/default/nope").status == 404
        assert factory.request(container, "/a/b/c").status == 404

    def test_handler_chain_order_first_wins(self):
        calls = []

        def probe(parser, runtime):
            calls.append(parser.url)
            return None  # decline; next handler runs

        factory, container, _ = _make_doc()
        router = RuntimeRequestRouter([probe,
                                       default_route_handler("default"),
                                       datastore_request_handler])
        response = router.request(container.runtime, "/")
        assert response.ok and calls == ["/"]

    def test_repeated_requests_return_cached_object_once_initialized(self):
        # Lifecycle hooks must not re-run per request — a has_initialized
        # that subscribes listeners would stack one copy per call.
        inits = []

        class Counting(DataObject):
            def initializing_first_time(self, props=None):
                pass

            def has_initialized(self):
                inits.append(1)

        factory = ContainerRuntimeFactoryWithDefaultDataStore(
            DataObjectFactory("counting", Counting))
        server = LocalCollabServer()
        container, created = factory.create_document(
            LocalDocumentService(server, "doc-cache"))
        container.attach()
        first = factory.request(container, "/").value
        second = factory.request(container, "/").value
        assert first is second is created
        assert inits == [1]  # only the create-time run

    def test_untyped_datastore_still_routes_raw(self):
        factory, container, _ = _make_doc()
        untyped = container.runtime.create_datastore("plain")
        untyped.create_channel("m", SharedMap.channel_type)
        response = factory.request(container, "/plain/m")
        assert response.ok and isinstance(response.value, SharedMap)


class TestSynthesize:
    def test_required_and_optional(self):
        deps = DependencyContainer()
        deps.register("ILogger", value="logger-instance")
        scope = deps.synthesize(required=["ILogger"],
                                optional=["IMissing"])
        assert scope.ILogger == "logger-instance"
        assert scope.IMissing is None

    def test_missing_required_raises(self):
        with pytest.raises(DependencyError):
            DependencyContainer().synthesize(required=["INope"])

    def test_factory_providers_are_lazy_singletons(self):
        built = []
        deps = DependencyContainer()
        deps.register("IThing", factory=lambda: built.append(1) or object())
        assert built == []
        first = deps.resolve("IThing")
        second = deps.resolve("IThing")
        assert first is second and built == [1]

    def test_parent_chaining_and_shadowing(self):
        parent = DependencyContainer()
        parent.register("IA", value="from-parent")
        parent.register("IB", value="parent-b")
        child = DependencyContainer(parent)
        child.register("IB", value="child-b")
        assert child.resolve("IA") == "from-parent"
        assert child.resolve("IB") == "child-b"
        assert parent.resolve("IB") == "parent-b"

    def test_register_validates_arguments(self):
        deps = DependencyContainer()
        with pytest.raises(ValueError):
            deps.register("IX")
        with pytest.raises(ValueError):
            deps.register("IX", value=1, factory=lambda: 2)
