"""SharedTree tests: id-anchored edits, invalid-edit dropping, rebase,
move/undo, convergence farm (BASELINE config 5 model)."""

import random

import pytest

from fluidframework_tpu.dds.tree import SharedTree
from fluidframework_tpu.dds.tree_core import ROOT_ID, VALID, INVALID
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_tree_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("tree", SharedTree.channel_type)
    container.attach()
    return container


def get_tree(container) -> SharedTree:
    return container.runtime.get_datastore("default").get_channel("tree")


def node(nid, payload=None, **traits):
    return {"id": nid, "definition": "n", "payload": payload,
            "traits": {k: list(v) for k, v in traits.items()}}


def end_of(parent, label="children"):
    return {"referenceTrait": {"parent": parent, "label": label},
            "side": "end"}


def range_of(nid):
    return {"start": {"referenceSibling": nid, "side": "before"},
            "end": {"referenceSibling": nid, "side": "after"}}


class TestTreeBasics:
    def test_insert_and_converge(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        t1.insert_node(node("a", payload=1), end_of(ROOT_ID))
        t2.insert_node(node("b", payload=2), end_of(ROOT_ID))
        assert t1.current_view.serialize() == t2.current_view.serialize()
        assert t1.current_view.children(ROOT_ID, "children") == ["a", "b"]
        assert c1.summarize() == c2.summarize()

    def test_set_value_and_move(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        t1.insert_node(node("x"), end_of(ROOT_ID))
        t1.insert_node(node("y"), end_of(ROOT_ID))
        t2.set_payload("x", {"deep": True})
        t2.move_range(range_of("x"),
                      {"referenceSibling": "y", "side": "after"})
        assert t1.current_view.children(ROOT_ID, "children") == ["y", "x"]
        assert t1.current_view.get("x").payload == {"deep": True}
        assert c1.summarize() == c2.summarize()

    def test_concurrent_edit_to_deleted_subtree_is_dropped(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        t1.insert_node(node("doomed", "alive"), end_of(ROOT_ID))
        c2.inbound.pause()
        t1.delete_range(range_of("doomed"))       # sequenced first
        t2.set_payload("doomed", "too late")      # anchored to a gone node
        c2.inbound.resume()
        assert not t1.current_view.has("doomed")
        assert not t2.current_view.has("doomed")
        # The late edit is recorded INVALID identically on both replicas.
        assert [e.validity for e in t1.log.sequenced] == \
               [e.validity for e in t2.log.sequenced]
        assert INVALID in [e.validity for e in t1.log.sequenced]
        assert c1.summarize() == c2.summarize()

    def test_local_pending_rebase_over_remote(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        t1.insert_node(node("base"), end_of(ROOT_ID))
        c1.inbound.pause()
        t2.insert_node(node("remote"), end_of(ROOT_ID))
        t1.insert_node(node("mine"), end_of(ROOT_ID))  # pending at c1
        # c1's local view shows its pending edit.
        assert "mine" in t1.current_view.nodes
        c1.inbound.resume()
        assert t1.current_view.serialize() == t2.current_view.serialize()
        assert t1.current_view.children(ROOT_ID, "children") == [
            "base", "remote", "mine"]

    def test_undo_of_insert_and_detach(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        eid = t1.insert_node(node("u", payload=7), end_of(ROOT_ID))
        assert t2.current_view.has("u")
        t1.undo(eid)
        assert not t1.current_view.has("u")
        assert not t2.current_view.has("u")
        # Undo a delete: the subtree comes back, same position.
        t1.insert_node(node("keep1"), end_of(ROOT_ID))
        t1.insert_node(node("mid", payload="m"), end_of(ROOT_ID))
        t1.insert_node(node("keep2"), end_of(ROOT_ID))
        del_id = t1.delete_range(range_of("mid"))
        assert not t1.current_view.has("mid")
        t2_del = [e for e in t2.log.sequenced if e.edit["id"] == del_id]
        assert t2_del and t2_del[0].validity == VALID
        t1.undo(del_id)
        assert t1.current_view.children(ROOT_ID, "children") == [
            "keep1", "mid", "keep2"]
        assert t1.current_view.get("mid").payload == "m"
        assert c1.summarize() == c2.summarize()

    def test_reconnect_replays_tree_edits(self):
        server = LocalCollabServer()
        c1 = make_tree_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        t1, t2 = get_tree(c1), get_tree(c2)
        t1.insert_node(node("a"), end_of(ROOT_ID))
        c2.disconnect()
        t2.insert_node(node("offline"), end_of(ROOT_ID))
        t1.set_payload("a", "changed while away")
        c2.reconnect()
        assert t1.current_view.serialize() == t2.current_view.serialize()
        assert t1.current_view.has("offline")
        assert c1.summarize() == c2.summarize()


@pytest.mark.parametrize("seed", range(3))
def test_tree_farm(seed):
    rng = random.Random(seed)
    server = LocalCollabServer()
    c1 = make_tree_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(2)]
    trees = [get_tree(c) for c in containers]
    counter = 0

    for _round in range(6):
        paused = [c for c in containers if rng.random() < 0.35]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(3, 8)):
            i = rng.randrange(len(trees))
            tree = trees[i]
            view = tree.current_view
            ids = [n for n in view.nodes if n != ROOT_ID]
            r = rng.random()
            if r < 0.45 or not ids:
                counter += 1
                anchor = rng.choice(ids) if ids and rng.random() < 0.5 else None
                dest = ({"referenceSibling": anchor, "side": "after"}
                        if anchor else end_of(ROOT_ID))
                tree.insert_node(node(f"n{i}-{counter}",
                                      payload=rng.randrange(100)), dest)
            elif r < 0.65:
                tree.set_payload(rng.choice(ids), rng.randrange(100))
            elif r < 0.85 and len(ids) >= 2:
                a, b = rng.sample(ids, 2)
                tree.move_range(range_of(a),
                                {"referenceSibling": b, "side": "after"})
            else:
                tree.delete_range(range_of(rng.choice(ids)))
        for c in paused:
            c.inbound.resume()
        views = [t.current_view.serialize() for t in trees]
        assert views[0] == views[1] == views[2], (seed, _round)
    summaries = [c.summarize() for c in containers]
    assert summaries[0] == summaries[1] == summaries[2], seed
