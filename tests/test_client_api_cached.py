"""Legacy client-api facade + odsp-analog caching driver."""

from __future__ import annotations

import pytest

from fluidframework_tpu import client_api
from fluidframework_tpu.drivers.cached_driver import (
    CachingDocumentService,
    EpochMismatchError,
)
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.server.local_server import LocalCollabServer


class TestClientApi:
    def test_create_edit_load_roundtrip(self):
        server = LocalCollabServer()
        doc = client_api.create(LocalDocumentService(server, "legacy"))
        root = doc.get_root()
        root.set("title", "client-api")
        text = doc.create_string()
        text.insert_text(0, "hello")
        root.set("text", text.handle)
        cell = doc.create_cell()
        cell.set(42)
        root.set("cell", cell.handle)

        other = client_api.load(
            lambda d: LocalDocumentService(server, d), "legacy")
        assert other.existing
        other_root = other.get_root()
        assert other_root.get("title") == "client-api"
        assert other_root.get("text").get().get_text() == "hello"
        assert other_root.get("cell").get().get() == 42

    def test_create_after_load_never_collides(self):
        # Channel ids are uuid-based (document.ts parity): a second
        # session creating channels on a loaded doc must not collide with
        # the first session's names, and `existing` distinguishes them.
        server = LocalCollabServer()
        doc = client_api.create(LocalDocumentService(server, "collide"))
        assert not doc.existing
        first = doc.create_string()
        doc.get_root().set("a", first.handle)

        again = client_api.load(
            lambda d: LocalDocumentService(server, d), "collide")
        assert again.existing
        second = again.create_string()
        second.insert_text(0, "late")
        again.get_root().set("b", second.handle)
        assert first.id != second.id
        assert doc.get_root().get("b").get().get_text() == "late"

    def test_all_creators(self):
        server = LocalCollabServer()
        doc = client_api.create(LocalDocumentService(server, "kinds"))
        matrix = doc.create_matrix()
        matrix.insert_rows(0, 1)
        matrix.insert_cols(0, 1)
        matrix.set_cell(0, 0, "x")
        directory = doc.create_directory()
        directory.set("k", 1)
        ink = doc.create_ink()
        root = doc.get_root()
        for name, channel in (("m", matrix), ("d", directory), ("i", ink)):
            root.set(name, channel.handle)
        again = client_api.load(
            lambda d: LocalDocumentService(server, d), "kinds")
        assert again.get_root().get("m").get().get_cell(0, 0) == "x"
        assert again.get_root().get("d").get().get("k") == 1


class TestCachingDriver:
    def _server_with_doc(self):
        server = LocalCollabServer()
        doc = client_api.create(LocalDocumentService(server, "doc"))
        doc.get_root().set("k", "v")
        # Persist a snapshot so loads have something to cache.
        server.upload_snapshot("doc", doc.container.summarize())
        return server, doc

    def test_snapshot_and_delta_caching(self):
        server, _doc = self._server_with_doc()
        service = CachingDocumentService(LocalDocumentService(server, "doc"))

        first = service.storage.get_latest_snapshot()
        again = service.storage.get_latest_snapshot()
        assert first is not None and again is first
        assert service.stats["snapshot_fetches"] == 1
        assert service.stats["snapshot_hits"] == 1

        all_deltas = service.delta_storage.get_deltas(0)
        assert all_deltas
        hit = service.delta_storage.get_deltas(
            0, all_deltas[-1].sequence_number)
        assert [m.sequence_number for m in hit] == \
            [m.sequence_number for m in all_deltas]
        assert service.stats["delta_hits"] >= 1

    def test_container_loads_through_cache(self):
        server, doc = self._server_with_doc()
        service = CachingDocumentService(LocalDocumentService(server, "doc"))
        from fluidframework_tpu.runtime.container import Container
        loaded = client_api.Document(Container.load(service),
                                     existing=True)
        assert loaded.get_root().get("k") == "v"
        # Live edits keep flowing through the caching connection...
        doc.get_root().set("k2", "v2")
        assert loaded.get_root().get("k2") == "v2"
        # ...and warmed the delta cache as they passed.
        assert service._cached_thru > 0

    def test_epoch_mismatch_flushes_and_retries(self):
        server, _doc = self._server_with_doc()
        epoch = {"value": 1}
        service = CachingDocumentService(
            LocalDocumentService(server, "doc"),
            epoch_source=lambda: epoch["value"])
        assert service.storage.get_latest_snapshot() is not None
        assert service._snapshot_cache is not None

        epoch["value"] = 2  # file restored/branched server-side
        with pytest.raises(EpochMismatchError) as err:
            service.storage.get_latest_snapshot()
        assert err.value.can_retry
        assert service._snapshot_cache is None  # flushed
        assert service.stats["epoch_flushes"] == 1

        # The retry (loader behavior on a retryable driver error) works.
        assert service.storage.get_latest_snapshot() is not None
