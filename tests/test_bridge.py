"""C++ front-door socket bridge (§2.9/§5.8 native transport): the same
wire protocol as alfred, sockets owned by native code."""

from __future__ import annotations

import subprocess
import sys
import time

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.tinylicious_driver import (
    TinyliciousDocumentServiceFactory,
)
from fluidframework_tpu.native.bridge import _load_library, start_bridge

pytestmark = pytest.mark.skipif(
    _load_library() is None, reason="no C++ toolchain for the bridge")
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.bridge_host import BridgeFrontDoor
from fluidframework_tpu.server.routerlicious import RouterliciousService


def test_native_bridge_builds_here():
    bridge = start_bridge()
    assert bridge is not None and bridge.port > 0
    bridge.stop()


def test_bridge_echo_roundtrip():
    import socket
    bridge = start_bridge()
    try:
        with socket.create_connection(("127.0.0.1", bridge.port)) as sock:
            sock.sendall(len(b"hello").to_bytes(4, "big") + b"hello")
            deadline = time.monotonic() + 10
            opened = data = None
            while time.monotonic() < deadline and data is None:
                event = bridge.poll()
                if event is None:
                    time.sleep(0.005)
                    continue
                if event[1] == 0:
                    opened = event[0]
                elif event[1] == 1:
                    data = event
            assert opened is not None and data is not None
            assert data[0] == opened and data[2] == b"hello"
            assert bridge.send(opened, b"world") == 0
            header = sock.recv(4)
            assert int.from_bytes(header, "big") == 5
            assert sock.recv(5) == b"world"
        # client hangup surfaces as CLOSE
        deadline = time.monotonic() + 10
        closed = False
        while time.monotonic() < deadline and not closed:
            event = bridge.poll()
            if event is not None and event[1] == 2:
                closed = True
            else:
                time.sleep(0.005)
        assert closed
    finally:
        bridge.stop()


def test_full_client_stack_over_bridge():
    """The network driver speaks to the C++ front door unchanged."""
    service = RouterliciousService()
    front = BridgeFrontDoor(service)
    try:
        factory = TinyliciousDocumentServiceFactory(port=front.port)
        svc1 = factory("bdoc")
        c1 = Container.create_detached(svc1)
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        with svc1.dispatch_lock:
            c1.attach()
            ds.get_channel("root").set("k", "via-bridge")
        deadline = time.monotonic() + 30
        while (c1.runtime.pending.has_pending
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert not c1.runtime.pending.has_pending

        svc2 = factory("bdoc")
        c2 = Container.load(svc2)
        with svc2.dispatch_lock:
            got = (c2.runtime.get_datastore("default")
                   .get_channel("root").get("k"))
        assert got == "via-bridge"

        # Cross-client live broadcast through the native transport.
        with svc1.dispatch_lock:
            ds.get_channel("root").set("k2", 7)

        def remote():
            with svc2.dispatch_lock:
                return (c2.runtime.get_datastore("default")
                        .get_channel("root").get("k2"))
        deadline = time.monotonic() + 30
        while remote() != 7 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert remote() == 7
        svc1.close()
        svc2.close()
    finally:
        front.close()


def test_service_initiated_disconnect_drops_transport():
    """A server-side disconnect (e.g. slow-consumer eviction) must close
    the client's socket — not leave it connected but silently deaf."""
    service = RouterliciousService()
    front = BridgeFrontDoor(service)
    try:
        factory = TinyliciousDocumentServiceFactory(port=front.port)
        svc = factory("dropdoc")
        c = Container.create_detached(svc)
        ds = c.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        with svc.dispatch_lock:
            c.attach()
        client_id = c.delta_manager.client_id
        assert client_id is not None
        dropped = []
        svc.events.on("disconnect", lambda: dropped.append(True))
        service.disconnect("dropdoc", client_id)
        deadline = time.monotonic() + 15
        while not dropped and time.monotonic() < deadline:
            time.sleep(0.02)
        assert dropped, "client never observed the server-side drop"
    finally:
        front.close()


def test_bridge_standalone_service():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.server.bridge_host",
         "--port", "0", "--no-merge-host"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("READY "), (line, proc.stderr.read())
        port = int(line.split()[1])
        factory = TinyliciousDocumentServiceFactory(port=port)
        svc = factory("sdoc")
        container = Container.create_detached(svc)
        ds = container.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        with svc.dispatch_lock:
            container.attach()
            ds.get_channel("root").set("x", 1)
        deadline = time.monotonic() + 60
        while (container.runtime.pending.has_pending
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert not container.runtime.pending.has_pending
        svc.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_per_connection_outbox_bound_is_class_scoped():
    """Round-13 satellite: bridge_set_conn_max_outbox overrides the -2
    threshold for ONE connection (the viewer class takes a shallow
    outbox) while other connections keep the bridge-wide default."""
    import socket

    from fluidframework_tpu.native.bridge import start_bridge

    bridge = start_bridge(0)
    try:
        viewer_sock = socket.create_connection(("127.0.0.1", bridge.port))
        writer_sock = socket.create_connection(("127.0.0.1", bridge.port))
        conns = []
        deadline = time.monotonic() + 15
        while len(conns) < 2 and time.monotonic() < deadline:
            ev = bridge.poll(wait_ms=50)
            if ev is not None and ev[1] == 0:  # EV_OPEN
                conns.append(ev[0])
        assert len(conns) == 2
        viewer_conn, writer_conn = conns
        assert bridge.set_conn_max_outbox(viewer_conn, 3) == 0
        assert bridge.set_conn_max_outbox(999999, 3) == -1
        # Stall both readers; flood. The viewer trips -2 at its shallow
        # bound; the writer keeps absorbing at the deep default.
        body = b"x" * 65536
        viewer_rc = writer_rc = 0
        for _ in range(64):
            if viewer_rc == 0:
                viewer_rc = bridge.send(viewer_conn, body)
            writer_rc = bridge.send(writer_conn, body)
            if viewer_rc == -2:
                break
        assert viewer_rc == -2
        assert writer_rc == 0
        # Resetting restores the default for later sends.
        assert bridge.set_conn_max_outbox(viewer_conn, None) == 0
        viewer_sock.close()
        writer_sock.close()
    finally:
        bridge.stop()


def test_stalled_reader_is_disconnected_not_silently_dropped():
    """bridge_send rc -2 (outbox full behind a reader that stopped
    reading): the front door must DISCONNECT the slow consumer — close
    its socket, close its service connection, count the drop — never
    drop the frame while leaving the connection up and silently deaf."""
    import socket

    from fluidframework_tpu.server.routerlicious import (
        RouterliciousService as Service,
    )

    service = Service()
    front = BridgeFrontDoor(service)
    try:
        front._bridge.set_max_outbox(4)  # trip -2 fast
        sock = socket.create_connection(("127.0.0.1", front.port))
        sock.settimeout(30)
        # Shrink the receive window so pushed frames back up quickly
        # behind a reader that never reads.
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        body = (b'{"rid": 1, "op": "connect", "doc_id": "slowdoc"}')
        sock.sendall(len(body).to_bytes(4, "big") + body)
        # Wait for the session + connection to exist server-side.
        deadline = time.monotonic() + 15
        session = None
        while time.monotonic() < deadline:
            sessions = list(front._sessions.values())
            if sessions and sessions[0].connection is not None:
                session = sessions[0]
                break
            time.sleep(0.01)
        assert session is not None, "connect never reached the service"
        # Stall: never read the socket again; push until the outbox bound
        # trips. The kernel buffers absorb the first frames, then sends
        # queue in the bridge outbox up to the (shrunk) bound.
        payload = {"event": "signal", "signal": {"pad": "x" * 8192}}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and session.connection is not None:
            session.push(payload)
        assert session.connection is None, \
            "slow consumer was never disconnected"
        assert front.metrics.counter("bridge.slow_consumer_drops").value >= 1
        # The transport really closed: the client's next read sees EOF.
        sock.settimeout(15)
        got = b"\x00"
        try:
            while got:  # drain whatever was delivered pre-drop
                got = sock.recv(65536)
        except (ConnectionResetError, socket.timeout):
            got = b""  # RST instead of FIN is an equally real close
        assert got == b""
        sock.close()
    finally:
        front.close()
