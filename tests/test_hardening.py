"""Runtime hardening: op chunking, blobs, delta scheduler, offline-resume
stash, driver retry/backoff.

Reference parity: containerRuntime.ts:1652 (submitChunkedMessage),
blobManager.ts:51, deltaScheduler.ts:25, pendingStateManager.ts stashed
ops / container.ts closeAndGetPendingLocalState, driver-utils
runWithRetry.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.drivers.utils import (
    NetworkError,
    ThrottlingError,
    run_with_retry,
)
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_doc(server, doc_id="doc", channels=(("root", SharedMap.channel_type),)):
    container = Container.create_detached(
        LocalDocumentService(server, doc_id))
    datastore = container.runtime.create_datastore("default")
    for name, channel_type in channels:
        datastore.create_channel(name, channel_type)
    container.attach()
    return container


def chan(container, name="root"):
    return container.runtime.get_datastore("default").get_channel(name)


class TestOpChunking:
    def test_oversized_op_chunks_and_converges(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        c1.runtime.max_op_bytes = 512  # force chunking at toy sizes
        big = "x" * 5000
        chan(c1).set("big", big)
        chan(c2).set("small", 1)

        assert chan(c2).get("big") == big
        assert dict(chan(c1).items()) == dict(chan(c2).items())
        assert c1.summarize() == c2.summarize()
        kinds = [m.type for m in server.get_deltas("doc", 0)]
        assert kinds.count(MessageType.CHUNKED_OP) >= 10
        assert not c1.nacks and not c2.nacks

    def test_chunked_op_replays_whole_after_offline_submit(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        c1.runtime.max_op_bytes = 512
        c1.disconnect()
        big = "y" * 4000
        chan(c1).set("offline-big", big)
        c1.reconnect()
        assert chan(c2).get("offline-big") == big
        assert c1.summarize() == c2.summarize()

    def test_late_joiner_reassembles_chunks(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c1.runtime.max_op_bytes = 256
        chan(c1).set("big", "z" * 3000)
        c3 = Container.load(LocalDocumentService(server, "doc"))
        assert chan(c3).get("big") == "z" * 3000


class TestBlobs:
    def test_upload_and_read_cross_client(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        handle = c1.runtime.blobs.upload_blob(b"\x00\x01binary payload")
        chan(c1).set("attachment", handle.absolute_path)

        path = chan(c2).get("attachment")
        blob_id = path.rsplit("/", 1)[1]
        assert c2.runtime.blobs.read(blob_id) == b"\x00\x01binary payload"

    def test_detached_blobs_upload_at_attach(self):
        server = LocalCollabServer()
        container = Container.create_detached(
            LocalDocumentService(server, "doc"))
        datastore = container.runtime.create_datastore("default")
        datastore.create_channel("root", SharedMap.channel_type)
        handle = container.runtime.blobs.upload_blob(b"early")
        assert handle.get() == b"early"  # readable pre-attach
        container.attach()
        c2 = Container.load(LocalDocumentService(server, "doc"))
        assert c2.runtime.blobs.read(handle.blob_id) == b"early"
        # The redirect table rides the summary.
        assert handle.blob_id in c2.summarize()["runtime"]["blobs"]["ids"]


class TestDeltaScheduler:
    def test_long_catchup_yields(self):
        server = LocalCollabServer()
        # Attach empty so the whole document (datastore included) arrives
        # as catch-up OPS — the manual container below loads no snapshot.
        c1 = Container.create_detached(LocalDocumentService(server, "doc"))
        c1.attach()
        c1.runtime.create_datastore("default").create_channel(
            "root", SharedMap.channel_type)
        for i in range(300):
            chan(c1).set(f"k{i % 10}", i)

        service = LocalDocumentService(server, "doc")
        c2 = Container(service)
        c2.attached = True
        yields = []
        c2.delta_manager.scheduler.on_yield.append(
            lambda done, left: yields.append((done, left)))
        c2.connect()
        assert yields, "no yields during a 300-op catch-up"
        assert c2.delta_manager.scheduler.catch_up_drains >= 1
        assert dict(chan(c2).items()) == dict(chan(c1).items())


class TestStashedPendingState:
    def test_offline_edits_resume_via_stash(self):
        server = LocalCollabServer()
        c1 = make_doc(server, channels=(
            ("root", SharedMap.channel_type),
            ("text", SharedString.channel_type)))
        c2 = Container.load(LocalDocumentService(server, "doc"))
        chan(c2, "text").insert_text(0, "base")

        c1.disconnect()
        chan(c1).set("offline", 1)
        chan(c1, "text").insert_text(0, "mine: ")
        stash = c1.close_and_get_pending_state()
        assert len(stash["pending"]) == 2

        c3 = Container.load(LocalDocumentService(server, "doc"),
                            pending_state=stash)
        assert chan(c3).get("offline") == 1
        assert chan(c3, "text").get_text() == chan(c2, "text").get_text()
        assert "mine: " in chan(c3, "text").get_text()
        assert c3.summarize() == c2.summarize()

    def test_sequenced_stashed_ops_ack_against_stash(self):
        """Ops the dead session DID get sequenced must not double-apply."""
        server = LocalCollabServer()
        c1 = make_doc(server, channels=(
            ("text", SharedString.channel_type),))
        c2 = Container.load(LocalDocumentService(server, "doc"))
        c1.inbound.pause()  # acks queue up unprocessed
        chan(c1, "text").insert_text(0, "sequenced!")
        stash = c1.close_and_get_pending_state()
        assert stash["pending"], "op should still be unacked"

        c3 = Container.load(LocalDocumentService(server, "doc"),
                            pending_state=stash)
        assert chan(c3, "text").get_text() == "sequenced!"  # not doubled
        assert chan(c2, "text").get_text() == "sequenced!"
        chan(c3, "text").insert_text(0, "go: ")
        assert chan(c2, "text").get_text() == "go: sequenced!"

    def test_matrix_stashed_ops(self):
        server = LocalCollabServer()
        c1 = make_doc(server, channels=(("grid", SharedMatrix.channel_type),))
        m1 = chan(c1, "grid")
        m1.insert_rows(0, 2)
        m1.insert_cols(0, 2)
        c2 = Container.load(LocalDocumentService(server, "doc"))

        c1.disconnect()
        m1.set_cell(0, 0, "stashed")
        m1.insert_rows(2, 1)
        stash = c1.close_and_get_pending_state()

        c3 = Container.load(LocalDocumentService(server, "doc"),
                            pending_state=stash)
        m2, m3 = chan(c2, "grid"), chan(c3, "grid")
        assert m3.row_count == m2.row_count == 3
        assert m2.get_cell(0, 0) == m3.get_cell(0, 0) == "stashed"
        assert c3.summarize() == c2.summarize()


class TestRetry:
    def test_retries_then_succeeds(self):
        calls = {"n": 0}
        delays = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("transient")
            return "ok"

        assert run_with_retry(flaky, sleep=delays.append) == "ok"
        assert calls["n"] == 3
        assert delays == [0.05, 0.1]  # exponential

    def test_non_retriable_raises_immediately(self):
        from fluidframework_tpu.drivers.utils import AuthorizationError

        def denied():
            raise AuthorizationError("401")

        with pytest.raises(AuthorizationError):
            run_with_retry(denied, sleep=lambda _d: None)

    def test_throttling_honors_retry_after(self):
        delays = []
        calls = {"n": 0}

        def throttled():
            calls["n"] += 1
            if calls["n"] == 1:
                raise ThrottlingError("429", retry_after_s=1.5)
            return "ok"

        assert run_with_retry(throttled, sleep=delays.append) == "ok"
        assert delays == [1.5]

    def test_gives_up_after_max_retries(self):
        def always():
            raise NetworkError("down")

        with pytest.raises(NetworkError):
            run_with_retry(always, max_retries=2, sleep=lambda _d: None)