"""Pallas merge-tree tick kernel: differential tests vs the XLA path.

The Pallas kernel (interpret mode on CPU) must produce byte-identical
planes to mergetree_kernel.apply_tick — which is itself pinned to the
sequential split/place spec and to live client replicas — on:
  * live SharedString op streams from the real client stack, and
  * randomized synthetic streams covering splits, overlapping removes,
    annotates, and concurrent-window visibility.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_pallas as mtp
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_mergetree import get_string, make_string_doc, random_edit
from tests.test_mergetree_kernel import encode_log


def _assert_states_equal(a: mtk.MergeState, b: mtk.MergeState, ctx) -> None:
    for field in mtk.MergeState._fields:
        fa = np.asarray(getattr(a, field))
        fb = np.asarray(getattr(b, field))
        assert np.array_equal(fa, fb), (ctx, field)


@pytest.mark.parametrize("seed", range(2))
def test_pallas_matches_xla_on_live_streams(seed):
    rng = random.Random(seed)
    n_docs = 3
    server = LocalCollabServer()
    docs = []
    for d in range(n_docs):
        c1 = make_string_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(2)]
        docs.append([c1] + others)

    for _round in range(4):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 8)):
                random_edit(rng, get_string(
                    containers[rng.randrange(len(containers))]))
            for c in paused:
                c.inbound.resume()

    pool = mtk.TextPool(n_docs)
    client_slots: dict = {}
    key_slots: dict = {}
    val_ids: dict = {}
    streams = [encode_log(server.get_deltas(f"doc{d}", 0), pool, d,
                          client_slots, key_slots, val_ids)
               for d in range(n_docs)]
    state_x = mtk.init_state(n_docs, num_slots=256)
    state_p = state_x
    k = 16
    longest = max(len(s) for s in streams)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        batch = mtk.make_merge_op_batch(chunk, n_docs, k)
        state_x = mtk.apply_tick(state_x, batch)
        state_p = mtp.apply_tick_pallas(
            state_p, batch, interpret=mtp.default_interpret())
    _assert_states_equal(state_x, state_p, seed)

    # And the converged text matches the replicas byte-for-byte.
    for d in range(n_docs):
        expected = get_string(docs[d][0]).get_text()
        got = mtk.materialize(state_p, pool, d).replace("\x00", "")
        assert got == expected, (seed, d)


@pytest.mark.parametrize("seed", range(3))
def test_pallas_matches_xla_on_random_streams(seed):
    rng = random.Random(9000 + seed)
    n_docs = rng.choice([1, 5, 9])  # exercises doc-axis padding too
    streams = []
    for _d in range(n_docs):
        ops = []
        length = 0
        for seq in range(1, rng.randrange(8, 30)):
            client = rng.randrange(5)
            ref_seq = rng.randrange(max(seq - 3, 0), seq)
            if length > 4 and rng.random() < 0.45:
                start = rng.randrange(length - 2)
                end = start + rng.randint(0, min(4, length - start))
                kind = rng.choice([mtk.MT_REMOVE, mtk.MT_ANNOTATE])
                op = dict(kind=kind, pos=start, end=end, seq=seq,
                          ref_seq=ref_seq, client=client)
                if kind == mtk.MT_ANNOTATE:
                    op.update(prop_key=rng.randrange(2),
                              prop_val=rng.randrange(1, 5))
                else:
                    length -= end - start
                ops.append(op)
            else:
                tlen = rng.randint(1, 4)
                ops.append(dict(kind=mtk.MT_INSERT,
                                pos=rng.randint(0, length), seq=seq,
                                ref_seq=ref_seq, client=client,
                                pool_start=seq * 10, text_len=tlen))
                length += tlen
        streams.append(ops)
    k = 8
    state_x = mtk.init_state(n_docs, num_slots=128, num_props=2)
    state_p = state_x
    longest = max(len(s) for s in streams)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        batch = mtk.make_merge_op_batch(chunk, n_docs, k)
        state_x = mtk.apply_tick(state_x, batch)
        state_p = mtp.apply_tick_pallas(
            state_p, batch, interpret=mtp.default_interpret())
    _assert_states_equal(state_x, state_p, seed)
