"""Broadcast viewer plane (server/broadcaster.py — the round-13
tentpole): read-only viewers ride fan-out rooms, broadcast frames
serialize once per doc per tick, slow viewers lag-drop to a
snapshot+catch-up resync, join storms gate through the TokenBucket
reservation ladder, and presence is interest-sampled."""

from __future__ import annotations

import numpy as np
import pytest

from fluidframework_tpu.protocol.codec import (
    decode_body,
    decode_storm_push,
    is_storm_body,
    ops_event_encode_count,
    pack_map_words,
)
from fluidframework_tpu.protocol.messages import DocumentMessage, MessageType
from fluidframework_tpu.server.broadcaster import ViewerPlane
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController


def _storm_stack(num_docs: int = 4, **storm_kw):
    seq_host = KernelSequencerHost(num_slots=2,
                                   initial_capacity=max(4, num_docs))
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=10**9, **storm_kw)
    return service, storm


def _words(k: int, seed: int = 0):
    return pack_map_words([0] * k, [(seed + i) % 16 for i in range(k)],
                          [7 + seed] * k).astype(np.uint32)


def _tick(storm, service, doc_clients, cseq0: int, k: int = 8,
          push=None, rid=0):
    entries = [[d, c, cseq0, 1, k] for d, c in doc_clients]
    payload = b"".join(_words(k, i).tobytes()
                       for i in range(len(doc_clients)))
    storm.submit_frame(push, {"rid": rid, "docs": entries},
                       memoryview(payload))
    storm.flush()


class _CollectingViewer:
    """In-process viewer transport: records every payload, decoding
    wire-shaped frames the way a socket client would."""

    def __init__(self):
        self.raw = []
        self.events = []

    def __call__(self, payload):
        self.raw.append(payload)
        if isinstance(payload, (bytes, bytearray)):
            self.events.append(decode_storm_push(payload)
                               if is_storm_body(payload)
                               else decode_body(payload))
        else:
            self.events.append(payload)

    def of(self, kind):
        return [e for e in self.events if isinstance(e, dict)
                and e.get("event") == kind]


class TestViewerStream:
    def test_viewer_receives_storm_tick_frames(self):
        service, storm = _storm_stack()
        writer = service.connect("doc", lambda m: None)
        service.pump()
        viewer = _CollectingViewer()
        conn = service.connect("doc", viewer, mode="viewer")
        assert conn.client_id.startswith("viewer-")
        assert conn.mode == "viewer"
        with pytest.raises(PermissionError):
            conn.submit([])

        _tick(storm, service, [("doc", writer.client_id)], cseq0=1)
        ticks = viewer.of("storm_tick")
        assert len(ticks) == 1
        t = ticks[0]
        assert t["doc"] == "doc" and t["n"] == 8
        assert t["last"] - t["first"] + 1 == 8
        assert list(t["words"]) == list(_words(8, 0))
        # Viewer connects never sequence a CLIENT_JOIN / enter the quorum
        # or connection map (no merge/ack bookkeeping at all).
        assert conn.client_id not in service._connections_for("doc")

        conn.close()
        _tick(storm, service, [("doc", writer.client_id)], cseq0=9)
        assert len(viewer.of("storm_tick")) == 1  # nothing after leave

    def test_serialize_once_invariant_encodes_per_tick_is_hot_docs(self):
        """THE acceptance invariant: broadcast encodes per tick == docs
        that ticked (with viewers), INDEPENDENT of viewer count."""
        service, storm = _storm_stack()
        docs = ["doc-a", "doc-b"]
        writers = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        viewers = [_CollectingViewer() for _ in range(64)]
        for i, v in enumerate(viewers):
            service.connect(docs[i % 2], v, mode="viewer")
        plane = service.viewers

        before = plane.stats["tick_encodes"]
        ticks = 3
        for t in range(ticks):
            _tick(storm, service, [(d, writers[d]) for d in docs],
                  cseq0=1 + t * 8)
        encodes = plane.stats["tick_encodes"] - before
        assert encodes == ticks * len(docs)  # NOT ticks * 64 viewers
        # Every viewer still received every tick of its doc — same bytes.
        for i, v in enumerate(viewers):
            frames = v.of("storm_tick")
            assert len(frames) == ticks
            assert all(f["doc"] == docs[i % 2] for f in frames)

    def test_per_op_path_shares_one_encode_per_batch(self):
        service = RouterliciousService()
        writer = service.connect("jdoc", lambda m: None)
        viewers = [_CollectingViewer() for _ in range(32)]
        for v in viewers:
            service.connect("jdoc", v, mode="viewer")
        before = ops_event_encode_count()
        writer.submit([DocumentMessage(
            type=MessageType.OPERATION, contents={"x": 1},
            client_sequence_number=1, reference_sequence_number=0)])
        service.pump()
        encodes = ops_event_encode_count() - before
        # One encode for the writer broadcast batch + one for the viewer
        # room — never one per subscriber.
        assert encodes <= 2
        for v in viewers:
            ops = v.of("ops")
            assert len(ops) >= 1


class TestLagDrop:
    def test_stalled_viewer_resyncs_without_stalling_writer(self):
        """A viewer whose transport backs up is LAG-DROPPED to a resync
        directive; the serving tick keeps acking the writer at full
        cadence, and healthy viewers keep streaming."""
        service, storm = _storm_stack()
        writer = service.connect("doc", lambda m: None)
        service.pump()
        healthy = _CollectingViewer()
        service.connect("doc", healthy, mode="viewer")
        stalled = _CollectingViewer()
        plane = service.viewers
        hello = plane.join("doc", stalled,
                           pending_probe=lambda: 10**9)  # transport full
        acks = []
        ticks = 4
        for t in range(ticks):
            _tick(storm, service, [("doc", writer.client_id)],
                  cseq0=1 + t * 8, push=acks.append, rid=t)
        # Writer path unaffected: every tick acked, fully sequenced.
        storm_acks = [a for a in acks if a.get("storm")]
        assert len(storm_acks) == ticks
        assert all(a["acks"][0][0] == 8 for a in storm_acks)
        # The stalled viewer was dropped once (not per tick) and told to
        # resync; the healthy viewer saw every tick.
        assert plane.stats["lag_drops"] == 1
        resyncs = stalled.of("viewer_resync")
        assert len(resyncs) == 1 and resyncs[0]["doc"] == "doc"
        assert len(healthy.of("storm_tick")) == ticks
        assert plane.room_size("doc") == 1

        # Resume re-enters the live stream (fresh subscriber, same id);
        # the gap up to resync["seq"] is the client's catch-up read.
        caught_up = service.get_deltas("doc", 0)
        seqs = [m.sequence_number for m in caught_up]
        # Contiguous through the whole gap (CLIENT_JOIN + every tick).
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
        assert seqs[-1] == 1 + ticks * 8
        resumed = plane.resume(hello["viewer_id"])
        # resync carried the stream position at DROP time; resume
        # returns the current head — the catch-up read covers between.
        assert resyncs[0]["seq"] == 9  # dropped during the first tick
        assert resumed["seq"] == 1 + ticks * 8
        stalled.raw.clear(), stalled.events.clear()
        stalled_probe_off = plane._viewers[hello["viewer_id"]]
        stalled_probe_off.pending_probe = None  # transport drained
        _tick(storm, service, [("doc", writer.client_id)],
              cseq0=1 + ticks * 8)
        assert len(stalled.of("storm_tick")) == 1

    def test_fanout_backlog_eviction_lag_drops(self):
        """The fan-out-queue side of lag detection: a viewer whose
        per-sub queue (the shallow viewer bound) overflows is evicted by
        the fan-out and lag-dropped at the next drain."""
        service = RouterliciousService()
        plane = ViewerPlane(service, max_lag_frames=4)
        v = _CollectingViewer()
        hello = plane.join("doc", v)
        sub = plane._viewers[hello["viewer_id"]].sub
        for i in range(6):  # overflow the shallow viewer bound
            plane.fanout.publish(plane._room("doc"), b"x%d" % i)
        assert plane.fanout.was_evicted(sub)
        plane._drain(["doc"])
        assert plane.stats["lag_drops"] == 1
        assert len(v.of("viewer_resync")) == 1

    def test_resync_gap_serves_from_cold_tier_without_hydrating(self):
        """The catch-up read a lag-dropped viewer performs rides the
        round-12 cold-read path: a doc evicted meanwhile serves its
        tick index from the cold head WITHOUT re-hydrating."""
        import tempfile

        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        from fluidframework_tpu.server.residency import ResidencyManager
        tmp = tempfile.mkdtemp(prefix="viewer-cold-")
        service, storm = _storm_stack(
            spill_dir=f"{tmp}/spill", durability="group",
            snapshots=GitSnapshotStore(f"{tmp}/git"))
        res = ResidencyManager(storm, idle_evict_s=1e9)
        writer = service.connect("cdoc", lambda m: None)
        service.pump()
        _tick(storm, service, [("cdoc", writer.client_id)], cseq0=1)
        service.disconnect("cdoc", writer.client_id)
        service.pump()
        res.evict("cdoc")
        assert not res.is_resident("cdoc")
        caught_up = service.get_deltas("cdoc", 0)
        ops = [m for m in caught_up if m.type == MessageType.OPERATION]
        assert len(ops) == 8  # the tick's sequenced window, from cold
        assert not res.is_resident("cdoc")  # a READ must not hydrate
        storm._group_wal.close()


class TestJoinStorm:
    def test_join_storm_ladders_at_bucket_rate(self):
        """100k-viewer live-event start, miniaturized: every refused
        join reserves a claimable slot; retries at the hint drain the
        herd at exactly the bucket rate (no compounding debt)."""
        import heapq

        clk = [0.0]
        service = RouterliciousService()
        rate = 50.0
        plane = ViewerPlane(service, join_rate_per_s=rate,
                            clock=lambda: clk[0])
        n = 400
        events = [(0.0, i) for i in range(n)]
        heapq.heapify(events)
        admitted_at: dict[int, float] = {}
        while events:
            t, i = heapq.heappop(events)
            clk[0] = t
            retry = plane.admit_join("event-doc", f"client-{i}")
            if retry is None:
                admitted_at[i] = t
            else:
                heapq.heappush(events, (t + retry, i))
        assert len(admitted_at) == n
        per_sec: dict[int, int] = {}
        for t in admitted_at.values():
            per_sec[int(t)] = per_sec.get(int(t), 0) + 1
        assert max(per_sec.values()) <= rate + plane.joins.burst
        makespan = max(admitted_at.values())
        ideal = n / rate
        assert makespan <= ideal * 1.5  # converges near the drain rate

    def test_claimed_reservation_is_not_redebited(self):
        clk = [0.0]
        service = RouterliciousService()
        plane = ViewerPlane(service, join_rate_per_s=1.0, join_burst=1.0,
                            clock=lambda: clk[0])
        assert plane.admit_join("d", "a") is None  # burst slot
        retry = plane.admit_join("d", "b")
        assert retry is not None  # refused, slot reserved
        # Early return: the SAME slot stands (no new debit).
        early = plane.admit_join("d", "b")
        assert early == pytest.approx(retry, abs=1e-6)
        clk[0] = retry + 1e-6
        assert plane.admit_join("d", "b") is None  # claims the slot
        assert plane.stats["join_nacks"] == 2


class TestPresence:
    def test_interest_sampled_presence_bounded(self):
        """Viewers see a bounded roster sample + an exact count; joins
        past the sample bound never fan one event per member."""
        service = RouterliciousService()
        plane = ViewerPlane(service, roster_sample=8)
        viewers = []
        n = 200
        for i in range(n):
            v = _CollectingViewer()
            plane.join("big-doc", v)
            viewers.append(v)
        first_snapshot = viewers[-1].of("viewer_presence")[0]
        assert first_snapshot["total"] == n
        assert len(first_snapshot["sample"]) <= 8
        # Coalesced announces: O(log) per audience doubling, not O(n).
        assert plane.stats["presence_updates"] < 50
        # No per-join event per member: the FIRST viewer saw far fewer
        # presence frames than there were joins.
        assert len(viewers[0].of("viewer_presence")) < 60

    def test_writer_audience_roster_is_bounded(self):
        from fluidframework_tpu.server.audience import (
            announce_connect, roster_sample)

        class _Conn:
            def __init__(self, cid):
                self.client_id = cid
                self.mode = "write"
                self.signals = []

            def on_signal(self, s):
                self.signals.append(s)

        conns = {f"c{i}": _Conn(f"c{i}") for i in range(20)}
        members, total = roster_sample(conns, limit=5)
        assert len(members) == 5 and total == 20
        newcomer = _Conn("new")
        conns["new"] = newcomer
        announce_connect(conns, newcomer, max_roster=5)
        snap = newcomer.signals[0]["content"]
        assert snap["event"] == "snapshot"
        assert len(snap["members"]) == 5 and snap["total"] == 21
        # Past the bound: peers get ONE count update (totals must not
        # drift), never a per-join member event.
        for c in conns.values():
            if c is newcomer:
                continue
            events = [s["content"]["event"] for s in c.signals]
            assert events == ["count"]
            assert c.signals[0]["content"]["total"] == 21
        # And a leave past the bound is a count update naming the
        # leaver — the decrement side of the same drift fix.
        from fluidframework_tpu.server.audience import announce_leave
        del conns["new"]
        announce_leave(conns, "new", max_roster=5)
        last = conns["c0"].signals[-1]["content"]
        assert last["event"] == "count"
        assert last["total"] == 20 and last["left"] == "new"


class TestViewerOverAlfred:
    def test_viewer_stream_over_the_wire(self):
        """e2e through the asyncio front door: mode="viewer" hello, ops
        events on the live stream, get_deltas catch-up + viewer_resume
        (the ViewerStream resync dance) — all over a real socket."""
        import subprocess
        import sys
        import time

        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService, ViewerStream)
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])

            svc = NetworkDocumentService("127.0.0.1", port, "live-doc")
            stream = ViewerStream(svc)
            hello = stream.connect()
            assert hello["viewer"] is True
            assert hello["client_id"].startswith("viewer-")

            writer_svc = NetworkDocumentService("127.0.0.1", port,
                                                "live-doc")
            writer = writer_svc.connect(lambda m: None)
            writer.submit([DocumentMessage(
                type=MessageType.OPERATION, contents={"k": 1},
                client_sequence_number=1, reference_sequence_number=0)])
            deadline = time.monotonic() + 30
            while stream.stats["ops"] == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert stream.stats["ops"] >= 1
            assert stream.last_seq >= 1

            # The resync dance over the wire (catch-up + viewer_resume).
            stream.lagged = True
            stream.last_seq = 0
            caught_up = stream.resync()
            assert [m.sequence_number for m in caught_up]
            assert not stream.lagged
            writer_svc.close()
            svc.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestServicePlaneRetention:
    """Round-13 satellite: the in-process bus partitions + per-doc ops
    store take an opt-in retention horizon (the BENCH_r12 residual
    ~11 KB/cold-doc tier)."""

    def test_bus_partitions_trim_below_slowest_group(self):
        from fluidframework_tpu.server.bus import Consumer, MessageBus
        bus = MessageBus(retention_messages=8)
        bus.create_topic("t", 1)
        fast = Consumer(bus, "t", "fast")
        slow = Consumer(bus, "t", "slow")
        for i in range(100):
            bus.produce("t", "k", i)
        msgs = fast.poll(0)
        fast.commit(0, msgs[-1].offset + 1)
        part = bus.topic("t").partitions[0]
        assert len(part.log) == 100  # the slow group pins the log
        half = slow.poll(0)[:50]
        slow.commit(0, half[-1].offset + 1)
        assert part.base == 50 and len(part.log) == 50
        slow.commit(0, 100)
        assert len(part.log) <= 8  # horizon tail retained
        # Reads from committed positions still work post-trim.
        for i in range(3):
            bus.produce("t", "k", 100 + i)
        assert [m.value for m in slow.poll(0)] == [100, 101, 102]

    def test_service_ops_store_horizon_bounds_history(self):
        service = RouterliciousService(ops_retention=16)
        writer = service.connect("rdoc", lambda m: None)
        for i in range(64):
            writer.submit([DocumentMessage(
                type=MessageType.OPERATION, contents={"i": i},
                client_sequence_number=i + 1,
                reference_sequence_number=0)])
        log = service.store.get("ops/rdoc", [])
        assert len(log) <= 32  # 2x horizon before each amortized trim
        # The tail stays contiguous and serves catch-up reads within
        # the horizon.
        tail = service.get_deltas("rdoc", log[0].sequence_number)
        seqs = [m.sequence_number for m in tail]
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))

    def test_restored_offsets_pin_retention_after_restart(self, tmp_path):
        """A group with a DURABLE offset pins the retention floor from
        the moment the bus reopens — even before its Consumer
        re-attaches — so a late re-attach never finds its position
        trimmed out from under it."""
        from fluidframework_tpu.server.bus import Consumer
        from fluidframework_tpu.server.durable_store import DurableMessageBus
        bus = DurableMessageBus(tmp_path / "bus", retention_messages=4)
        bus.create_topic("t", 1)
        fast = Consumer(bus, "t", "fast")
        slow = Consumer(bus, "t", "slow")
        for i in range(50):
            bus.produce("t", "k", i)
        fast.commit(0, 50)
        slow.commit(0, 10)
        bus.close()

        bus2 = DurableMessageBus(tmp_path / "bus", retention_messages=4)
        bus2.create_topic("t", 1)
        # Only the fast group re-attaches and commits further...
        fast2 = Consumer(bus2, "t", "fast")
        for i in range(50, 60):
            bus2.produce("t", "k", i)
        fast2.commit(0, 60)
        # ...but "slow"'s durable offset (10) pinned the floor: its late
        # re-attach still reads from exactly where it left off.
        slow2 = Consumer(bus2, "t", "slow")
        values = [m.value for m in slow2.poll(0)]
        assert values[:3] == [10, 11, 12]
        assert len(values) == 50
        bus2.close()

    def test_bus_retention_keeps_service_plane_ram_bounded(self):
        """The closing evidence for BENCH_r12's residual slope: with the
        horizon on, a long op stream leaves O(horizon) messages in the
        bus partitions instead of O(history)."""
        from fluidframework_tpu.server.bus import MessageBus
        bus = MessageBus(retention_messages=32)
        service = RouterliciousService(bus=bus, ops_retention=32)
        writer = service.connect("bdoc", lambda m: None)
        for i in range(200):
            writer.submit([DocumentMessage(
                type=MessageType.OPERATION, contents={"i": i},
                client_sequence_number=i + 1,
                reference_sequence_number=0)])
        retained = sum(len(p.log) for t in bus._topics.values()
                       for p in t.partitions)
        assert retained <= 4 * 2 * 32 + 64  # partitions x topics x horizon
