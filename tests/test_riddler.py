"""Riddler auth/tenancy/throttling + foreman/copier lambdas.

Reference parity: alfred's JWT gate (alfred/index.ts:343), riddler tenant
service, services-core IThrottler; foreman/lambda.ts help-task
assignment; copier raw-op archival.
"""

from __future__ import annotations

import asyncio
import threading

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.network_driver import NetworkDocumentService
from fluidframework_tpu.drivers.utils import ThrottlingError
from fluidframework_tpu.protocol.messages import MessageType, ScopeType
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.riddler import (
    AuthError,
    TenantManager,
    Throttler,
    sign_token,
)
from fluidframework_tpu.server.routerlicious import RouterliciousService


class TestTokens:
    def test_sign_validate_roundtrip(self):
        tenants = TenantManager()
        tenant = tenants.create_tenant("acme")
        token = sign_token("acme", tenant.secret, "doc1",
                           list(ScopeType.ALL), user="alice")
        claims = tenants.validate_token(token, document_id="doc1")
        assert claims["scopes"] == list(ScopeType.ALL)
        assert claims["user"] == "alice"

    def test_tampered_token_rejected(self):
        import json

        from fluidframework_tpu.server.riddler import _b64url, _unb64url

        tenants = TenantManager()
        tenant = tenants.create_tenant("acme")
        token = sign_token("acme", tenant.secret, "doc1", ["doc:read"])
        header, claims_b64, sig = token.split(".")
        claims = json.loads(_unb64url(claims_b64))
        claims["scopes"] = ["doc:write", "summary:write"]  # escalate
        evil = _b64url(json.dumps(claims, sort_keys=True).encode())
        with pytest.raises(AuthError):
            tenants.validate_token(f"{header}.{evil}.{sig}")

    def test_expired_token_rejected(self):
        tenants = TenantManager()
        tenant = tenants.create_tenant("acme")
        token = sign_token("acme", tenant.secret, "doc1", ["doc:read"],
                           lifetime_s=10, now=1000.0)
        tenants.validate_token(token, now=1005.0)
        with pytest.raises(AuthError):
            tenants.validate_token(token, now=1011.0)

    def test_wrong_document_rejected(self):
        tenants = TenantManager()
        tenant = tenants.create_tenant("acme")
        token = sign_token("acme", tenant.secret, "doc1", ["doc:read"])
        with pytest.raises(AuthError):
            tenants.validate_token(token, document_id="other")

    def test_unknown_tenant_and_wrong_secret(self):
        tenants = TenantManager()
        tenants.create_tenant("acme")
        with pytest.raises(AuthError):
            tenants.validate_token(
                sign_token("ghost", "s", "doc1", []))
        with pytest.raises(AuthError):
            tenants.validate_token(
                sign_token("acme", "wrong-secret", "doc1", []))

    def test_tenants_persist_in_store(self):
        from fluidframework_tpu.server.bus import StateStore
        store = StateStore()
        tenant = TenantManager(store).create_tenant("acme")
        reopened = TenantManager(store)
        token = sign_token("acme", tenant.secret, "doc1", ["doc:read"])
        assert reopened.validate_token(token)["tenantId"] == "acme"


class TestThrottler:
    def test_window_limits_and_resets(self):
        clock = {"t": 0.0}
        throttler = Throttler(rate_per_interval=3, interval_s=1.0,
                              clock=lambda: clock["t"])
        assert throttler.try_consume("k") is None
        assert throttler.try_consume("k", weight=2) is None
        retry = throttler.try_consume("k")
        assert retry is not None and 0 < retry <= 1.0
        clock["t"] = 1.1  # window rolls
        assert throttler.try_consume("k") is None

    def test_keys_are_independent(self):
        throttler = Throttler(rate_per_interval=1, interval_s=60)
        assert throttler.try_consume("a") is None
        assert throttler.try_consume("b") is None
        assert throttler.try_consume("a") is not None


class TestSecureFrontDoor:
    def test_valid_token_connects_and_edits(self, secure_alfred):
        port, tenant = secure_alfred
        token = sign_token("acme", tenant.secret, "doc",
                           list(ScopeType.ALL))
        svc = NetworkDocumentService("127.0.0.1", port, "doc", token=token)
        c1 = Container.create_detached(svc)
        c1.runtime.create_datastore("default").create_channel(
            "root", SharedMap.channel_type)
        with svc.dispatch_lock:
            c1.attach()
            c1.runtime.get_datastore("default").get_channel(
                "root").set("k", 1)
        svc.close()

    def test_missing_and_invalid_token_rejected(self, secure_alfred):
        port, tenant = secure_alfred
        svc = NetworkDocumentService("127.0.0.1", port, "doc2")
        with pytest.raises(RuntimeError, match="token"):
            svc.connect(lambda ms: None)
        svc.close()

        bad = sign_token("acme", "not-the-secret", "doc2", ["doc:read"])
        svc = NetworkDocumentService("127.0.0.1", port, "doc2", token=bad)
        with pytest.raises(RuntimeError, match="signature"):
            svc.connect(lambda ms: None)
        svc.close()

    def test_token_for_other_document_rejected(self, secure_alfred):
        port, tenant = secure_alfred
        token = sign_token("acme", tenant.secret, "doc-A", ["doc:read"])
        svc = NetworkDocumentService("127.0.0.1", port, "doc-B", token=token)
        with pytest.raises(RuntimeError, match="bound"):
            svc.connect(lambda ms: None)
        svc.close()

    def test_submit_throttled(self, secure_alfred):
        port, tenant = secure_alfred
        token = sign_token("acme", tenant.secret, "tdoc",
                           list(ScopeType.ALL))
        svc = NetworkDocumentService("127.0.0.1", port, "tdoc", token=token)
        from fluidframework_tpu.protocol.messages import DocumentMessage
        conn = svc.connect(lambda ms: None)
        msg = DocumentMessage(client_sequence_number=1,
                              reference_sequence_number=1,
                              type=MessageType.NOOP, contents="")
        with pytest.raises(ThrottlingError) as err:
            for i in range(200):
                conn.submit([msg])
        assert err.value.retry_after_s > 0
        svc.close()


class TestForemanCopier:
    def test_copier_archives_raw_ops(self):
        service = RouterliciousService()
        conn = service.connect("doc", lambda ms: None)
        conn.submit([_doc_msg(1, MessageType.OPERATION, {"x": 1})])
        raw = service.store.get("rawops/doc")
        assert raw, "copier wrote nothing"
        kinds = [r.type for r in raw]
        assert MessageType.CLIENT_JOIN in kinds
        assert MessageType.OPERATION in kinds

    def test_foreman_assigns_help_tasks_round_robin(self):
        service = RouterliciousService(help_agents=["agent-a", "agent-b"])
        conn = service.connect("doc", lambda ms: None)
        conn.submit([_doc_msg(1, MessageType.REMOTE_HELP,
                              {"tasks": ["spell", "translate", "ocr"]})])
        assignments = service.store.get("help/doc")
        assert [a["task"] for a in assignments] == \
            ["spell", "translate", "ocr"]
        assert [a["agent"] for a in assignments] == \
            ["agent-a", "agent-b", "agent-a"]
        # Replayed/duplicate ops don't double-assign.
        conn.submit([_doc_msg(2, MessageType.NOOP, "")])
        assert len(service.store.get("help/doc")) == 3


def _doc_msg(client_seq, mtype, contents):
    from fluidframework_tpu.protocol.messages import DocumentMessage
    return DocumentMessage(client_sequence_number=client_seq,
                           reference_sequence_number=1,
                           type=mtype, contents=contents)
