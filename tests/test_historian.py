"""Historian caching proxy: read-through LRU over the snapshot store."""

from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.historian import Historian


class _CountingBackend:
    """Wraps a GitSnapshotStore counting backend object reads."""

    def __init__(self, store):
        self._store = store
        self.object_reads = 0

    def __getattr__(self, name):
        return getattr(self._store, name)

    def get_object(self, sha):
        self.object_reads += 1
        return self._store.get_object(sha)


class TestHistorian:
    def test_upload_warms_cache_and_reads_hit(self, tmp_path):
        backend = _CountingBackend(GitSnapshotStore(tmp_path))
        historian = Historian(backend)
        handle = historian.upload("doc", {"text": "hello" * 100})
        historian.set_head("doc", handle)

        # Upload wrote through the cache: reads never touch the backend.
        first = historian.get("doc", handle)
        assert first == {"text": "hello" * 100}
        assert backend.object_reads == 0
        assert historian.get("doc", handle) == first
        assert backend.object_reads == 0
        assert historian.stats()["object_hits"] > 0

    def test_cold_historian_reads_through(self, tmp_path):
        store = GitSnapshotStore(tmp_path)
        handle = store.upload("doc", {"text": "cold"})
        backend = _CountingBackend(store)
        historian = Historian(backend)
        assert historian.get("doc", handle) == {"text": "cold"}
        reads = backend.object_reads
        assert reads > 0
        assert historian.get("doc", handle) == {"text": "cold"}
        assert backend.object_reads == reads  # second read fully cached

    def test_head_write_through_and_ttl(self, tmp_path):
        now = [0.0]
        backend = GitSnapshotStore(tmp_path)
        historian = Historian(backend, head_ttl_s=5.0,
                              clock=lambda: now[0])
        h1 = historian.upload("doc", {"v": 1})
        historian.set_head("doc", h1)
        assert historian.head("doc") == h1

        # A second historian (another service instance) writes a new head;
        # ours serves the stale cached head until the TTL lapses.
        other = Historian(backend, head_ttl_s=5.0, clock=lambda: now[0])
        h2 = other.upload("doc", {"v": 2})
        other.set_head("doc", h2)
        assert historian.head("doc") == h1
        now[0] += 6.0
        assert historian.head("doc") == h2

    def test_lru_eviction_bounds(self, tmp_path):
        backend = GitSnapshotStore(tmp_path)
        historian = Historian(backend, max_objects=4, max_bytes=10_000)
        shas = [historian.put_object(f"payload-{i}".encode() * 50)
                for i in range(10)]
        stats = historian.stats()
        assert stats["objects"] <= 4
        assert stats["bytes"] <= 10_000
        assert stats["evictions"] > 0
        # Evicted objects still readable (read-through).
        assert historian.get_object(shas[0]).startswith(b"payload-0")

    def test_oversized_object_served_not_cached(self, tmp_path):
        backend = GitSnapshotStore(tmp_path)
        historian = Historian(backend, max_objects=8, max_bytes=100)
        sha = historian.put_object(b"x" * 1000)
        assert historian.get_object(sha) == b"x" * 1000
        assert historian.stats()["objects"] == 0

    def test_service_snapshot_path_through_historian(self, tmp_path):
        # The durable service assembly wraps snapshots in a historian;
        # summary write + late-joiner read must round-trip through it.
        from fluidframework_tpu.server.alfred import build_default_service
        service = build_default_service(str(tmp_path), merge_host=False)
        service.upload_snapshot("doc", {"tree": {"a": 1}})
        assert service.get_latest_snapshot("doc") == {"tree": {"a": 1}}
        assert service.get_latest_snapshot("doc") == {"tree": {"a": 1}}
        assert service.snapshots.stats()["object_hits"] > 0
