"""Summary subsystem tests: scribe ack flow, oldest-client election,
ops-count heuristics, load-from-acked-summary, and nack recovery.

Reference parity model: summarizer.ts / summaryManager.ts heuristics +
scribe/lambda.ts summary write + summaryAck, and the rule that only ACKED
summaries are load-visible to new clients.
"""

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.protocol.messages import MessageType, ScopeType
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.runtime.summarizer import SummaryConfig, SummaryManager
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_doc(server, doc_id="doc", scopes=None):
    service = LocalDocumentService(server, doc_id, scopes=scopes)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("root", SharedMap.channel_type)
    datastore.create_channel("clicks", SharedCounter.channel_type)
    container.attach()
    return container


def open_doc(server, doc_id="doc", scopes=None):
    return Container.load(LocalDocumentService(server, doc_id, scopes=scopes))


def root_of(c):
    return c.runtime.get_datastore("default").get_channel("root")


def test_manual_summary_acked_and_load_visible():
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    root_of(c1).set("x", 1)
    handle = sm.summarize_now(reason="test")
    assert handle is not None
    # The ack was sequenced and observed; in-flight state cleared.
    assert sm.pending_handle is None
    kinds = [e.kind for e in sm.events]
    assert kinds == ["generated", "acked"]
    # A fresh client loads from the acked summary, not the attach base.
    c2 = open_doc(server)
    assert root_of(c2).get("x") == 1
    assert c1.summarize() == c2.summarize()


def test_incremental_summary_uploads_only_changed_channels():
    """After an acked summary, changing 1 of 100 channels must serialize
    ~1 channel: the other 99 ride as handle stubs into the acked parent
    (summary.ts:53 handle reuse), and the service resolves them so new
    clients still load a full tree."""
    import json

    server = LocalCollabServer()
    service = LocalDocumentService(server, "doc")
    c1 = Container.create_detached(service)
    ds = c1.runtime.create_datastore("default")
    for i in range(100):
        ds.create_channel(f"ch{i}", SharedMap.channel_type)
    c1.attach()
    for i in range(100):  # fill every channel with real content
        ds.get_channel(f"ch{i}").set("payload", "x" * 1000)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    uploads = []
    original = service.storage.upload_snapshot

    def spy(snapshot, parent=None):
        uploads.append((json.dumps(snapshot, default=list), parent))
        return original(snapshot, parent)

    service.storage.upload_snapshot = spy
    h1 = sm.summarize_now(reason="base")
    assert h1 is not None and uploads[-1][1] is None  # full, no parent

    ds.get_channel("ch42").set("changed", True)
    h2 = sm.summarize_now(reason="delta")
    assert h2 is not None
    body, parent = uploads[-1]
    assert parent == h1  # resolved against the acked base
    full_body = uploads[0][0]
    # ~1/100th the bytes: one channel inline, 99 handle stubs.
    assert len(body) < len(full_body) / 10, (len(body), len(full_body))
    from fluidframework_tpu.protocol.summary import count_handles
    assert count_handles(json.loads(body)) == 99
    # New clients load the RESOLVED tree — identical to the live replica.
    c2 = open_doc(server)
    assert c2.summarize() == c1.summarize()
    assert c2.runtime.get_datastore("default").get_channel(
        "ch42").get("changed") is True
    assert c2.runtime.get_datastore("default").get_channel(
        "ch7").get("payload") == "x" * 1000


def test_incremental_summary_includes_channels_created_after_base():
    """A channel born after the acked summary must serialize inline —
    a handle stub would dangle in the parent."""
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    root_of(c1).set("x", 1)
    assert sm.summarize_now(reason="base") is not None
    ds = c1.runtime.get_datastore("default")
    fresh = ds.create_channel("newcomer", SharedMap.channel_type)
    fresh.set("born", "late")
    assert sm.summarize_now(reason="delta") is not None
    c2 = open_doc(server)
    assert c2.runtime.get_datastore("default").get_channel(
        "newcomer").get("born") == "late"
    assert c1.summarize() == c2.summarize()


def test_user_content_shaped_like_a_handle_is_not_resolved():
    """Handle resolution is structural (channel positions only): a USER
    value {'_handle': ...} inside changed channel content must survive
    the incremental round trip untouched — no in-band collision."""
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    root_of(c1).set("seed", 1)
    assert sm.summarize_now(reason="base") is not None
    root_of(c1).set("cfg", {"_handle": "protocol"})  # looks like a stub
    assert sm.summarize_now(reason="delta") is not None
    c2 = open_doc(server)
    assert root_of(c2).get("cfg") == {"_handle": "protocol"}
    assert c1.summarize() == c2.summarize()


def test_unacked_upload_not_load_visible():
    server = LocalCollabServer()
    c1 = make_doc(server)
    root_of(c1).set("x", 1)
    # Upload WITHOUT offering it through the sequenced summarize op.
    c1._service.storage.upload_snapshot(c1.summarize())
    c2 = open_doc(server)
    # c2 still converges — via the attach base + trailing deltas.
    assert root_of(c2).get("x") == 1
    assert c1.summarize() == c2.summarize()


def test_heuristics_trigger_at_max_ops():
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=5))
    m = root_of(c1)
    for i in range(4):
        m.set(f"k{i}", i)
    assert [e.kind for e in sm.events] == []
    m.set("k4", 4)  # fifth op crosses the threshold
    assert [e.kind for e in sm.events] == ["generated", "acked"]
    # Counter reset: no immediate re-summary.
    m.set("k5", 5)
    assert len(sm.events) == 2


def test_only_oldest_eligible_client_summarizes():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = open_doc(server)
    sm1 = SummaryManager(c1, SummaryConfig(max_ops=3))
    sm2 = SummaryManager(c2, SummaryConfig(max_ops=3))
    assert sm1.is_elected and not sm2.is_elected
    for i in range(6):
        root_of(c2).set(f"k{i}", i)
    # Six ops at threshold 3 = two complete summary cycles, all by c1.
    assert [e.kind for e in sm1.events if e.kind == "generated"] == \
        ["generated", "generated"]
    assert [e.kind for e in sm2.events if e.kind == "generated"] == []
    # Both observed the ack and reset their counters identically.
    assert sm1.ops_since_ack == sm2.ops_since_ack


def test_election_falls_over_on_leave():
    server = LocalCollabServer()
    c1 = make_doc(server)
    c2 = open_doc(server)
    sm2 = SummaryManager(c2, SummaryConfig(max_ops=3))
    assert not sm2.is_elected
    c1.close()
    root_of(c2).set("after", 1)  # leave processed; c2 now oldest
    assert sm2.is_elected
    for i in range(3):
        root_of(c2).set(f"k{i}", i)
    assert "generated" in [e.kind for e in sm2.events]


def test_clients_without_summary_scope_not_elected():
    server = LocalCollabServer()
    scopes = (ScopeType.READ, ScopeType.WRITE)
    c1 = make_doc(server, scopes=scopes)  # oldest but ineligible
    c2 = open_doc(server)                 # full scopes
    sm1 = SummaryManager(c1, SummaryConfig(max_ops=3))
    sm2 = SummaryManager(c2, SummaryConfig(max_ops=3))
    assert not sm1.is_elected
    assert sm2.is_elected
    for i in range(4):
        root_of(c1).set(f"k{i}", i)
    assert [e.kind for e in sm1.events if e.kind == "generated"] == []
    assert "generated" in [e.kind for e in sm2.events]


def test_bad_handle_nacked_then_retries():
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=4))
    # Forge an offer with a bogus handle (simulates a lost upload).
    c1.submit_message(MessageType.SUMMARIZE,
                      {"handle": "no/such/handle", "head": 0})
    assert [e.kind for e in sm.events] == ["nacked"]
    # Heuristics recover: the next threshold crossing summarizes for real.
    m = root_of(c1)
    for i in range(6):
        m.set(f"k{i}", i)
    assert [e.kind for e in sm.events][-2:] == ["generated", "acked"]
    c2 = open_doc(server)
    assert c1.summarize() == c2.summarize()


def test_stale_summary_offer_cannot_roll_back():
    # Re-offering an OLD handle must be nacked, not roll acked state back.
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    m = root_of(c1)
    m.set("a", 1)
    old_handle = sm.summarize_now()
    m.set("b", 2)
    new_handle = sm.summarize_now()
    assert server.get_latest_snapshot("doc")["sequence_number"] == \
        server._documents["doc"].snapshots[new_handle]["sequence_number"]
    c1.submit_message(MessageType.SUMMARIZE, {"handle": old_handle})
    assert sm.events[-1].kind == "nacked"
    # Latest acked snapshot unchanged; a joiner loads the NEW one.
    c2 = open_doc(server)
    assert root_of(c2).get("b") == 2
    assert c1.summarize() == c2.summarize()


def test_peer_nack_does_not_cancel_own_offer():
    # A peer's rejected offer must not clear the elected client's in-flight
    # tracking: correlated by handle.
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    sm.pending_handle = "in/flight"  # simulate an offer awaiting its ack
    sm.pending_since_seq = c1.last_processed_seq
    c1.submit_message(MessageType.SUMMARIZE, {"handle": "bogus"})
    assert sm.events[-1].kind == "nacked"
    assert sm.pending_handle == "in/flight"  # untouched


def test_no_summary_while_local_ops_pending():
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=2))
    c1.disconnect()
    m = root_of(c1)
    m.set("offline", 1)  # optimistic, unacked
    assert c1.runtime.pending.has_pending
    assert sm.summarize_now() is None
    c1.connect()
    c1.runtime.replay_pending()
    assert not c1.runtime.pending.has_pending
    # Clean state summarizes fine.
    assert sm.summarize_now() is not None
    c2 = open_doc(server)
    assert c1.summarize() == c2.summarize()


def test_ack_wait_timeout_unsticks_summaries():
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=3, max_ack_wait_ops=5))
    sm.pending_handle = "lost/offer"  # its ack will never arrive
    sm.pending_since_seq = c1.last_processed_seq
    m = root_of(c1)
    for i in range(12):
        m.set(f"k{i}", i)
    # After the wait expired, heuristics resumed and a real summary landed.
    assert "acked" in [e.kind for e in sm.events]


def test_summary_compacts_catchup_reads():
    # After an acked summary at seq N, a fresh client needs only deltas > N.
    server = LocalCollabServer()
    c1 = make_doc(server)
    sm = SummaryManager(c1, SummaryConfig(max_ops=10_000))
    m = root_of(c1)
    for i in range(20):
        m.set(f"k{i}", i)
    sm.summarize_now()
    snap = server.get_latest_snapshot("doc")
    trailing = server.get_deltas("doc", snap["sequence_number"])
    # Only the summarize + ack trail the snapshot.
    assert len(trailing) <= 2
    c2 = open_doc(server)
    assert c1.summarize() == c2.summarize()
