"""Pipelined-cadence stall hunt (VERDICT r4 weak #1): sweep pipeline
depth and probe style over >=120-tick series on the map-storm shape and
print the full interval distribution, to find why the depth-4 pipe
periodically stalls for a full tunnel RTT (p50 2.3ms vs p99 98ms).

Run on the real TPU attachment:  python tools/p99_probe.py
"""

import sys
import time

import numpy as np


def main(num_docs=10_240, k=1024, slots=32, ticks=120):
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops import map_kernel as mk
    from fluidframework_tpu.ops import map_pallas as mpx

    rng = np.random.default_rng(0)
    batches = []
    for _t in range(12):
        kinds = rng.choice([mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
                           p=[0.75, 0.2, 0.05],
                           size=(num_docs, k)).astype(np.uint32)
        slot = rng.integers(0, slots, (num_docs, k)).astype(np.uint32)
        value = rng.integers(1, 1 << 20, (num_docs, k)).astype(np.uint32)
        words = kinds | (slot << 2) | (value << 12)
        counts = np.full((num_docs,), k, np.int32)
        base = np.full((num_docs,), 0, np.int32)
        batches.append(tuple(jax.device_put(a)
                             for a in (words, counts, base)))
    state0 = mk.init_state(num_docs, slots)

    def apply_plain(s, b):
        return mpx.apply_tick_words_best(s, *b), None

    @jax.jit
    def apply_fused_probe(s, words, counts, base):
        s = mpx.apply_tick_words_best(s, words, counts, base)
        # Probe scalar computed INSIDE the tick executable: harvesting it
        # costs no extra launch (the slice-on-host probe is its own tiny
        # dispatch over the tunnel).
        return s, s.value[0, 0] + s.vseq[0, 0]

    def apply_fused(s, b):
        s, probe = apply_fused_probe(s, *b)
        return s, probe

    for name, apply in (("slice-probe", apply_plain),
                        ("fused-probe", apply_fused)):
        for depth in (4, 8, 16, 32):
            s = state0
            inflight = []
            completions = []
            for i in range(ticks + depth):
                s, probe = apply(s, batches[i % len(batches)])
                if probe is None:
                    leaf = jax.tree_util.tree_leaves(s)[0]
                    probe = leaf[(0,) * leaf.ndim]
                copy_async = getattr(probe, "copy_to_host_async", None)
                if copy_async is not None:
                    copy_async()
                inflight.append(probe)
                if len(inflight) > depth:
                    np.asarray(inflight.pop(0))
                    completions.append(time.perf_counter())
            while inflight:
                np.asarray(inflight.pop(0))
                completions.append(time.perf_counter())
            d = np.diff(np.asarray(completions[:ticks])) * 1000
            big = np.sort(d)[-8:]
            print(f"{name} depth={depth:2d} n={len(d)} "
                  f"p50={np.percentile(d, 50):7.2f} "
                  f"p90={np.percentile(d, 90):7.2f} "
                  f"p99={np.percentile(d, 99):7.2f} "
                  f"max={d.max():7.2f} stalls>{25}ms="
                  f"{int((d > 25).sum())} top={np.round(big, 1)}")
            sys.stdout.flush()


if __name__ == "__main__":
    main()
