"""Paced-enqueue cadence experiment: does offering ticks at a fixed
interval (load < capacity) smooth the completion stream, or does the
tunnel deliver result copies in bursts regardless?"""

import sys
import time

import numpy as np


def main(num_docs=10_240, k=1024, slots=32, ticks=120):
    import jax

    from fluidframework_tpu.ops import map_kernel as mk
    from fluidframework_tpu.ops import map_pallas as mpx

    rng = np.random.default_rng(0)
    batches = []
    for _t in range(12):
        kinds = rng.choice([mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
                           p=[0.75, 0.2, 0.05],
                           size=(num_docs, k)).astype(np.uint32)
        slot = rng.integers(0, slots, (num_docs, k)).astype(np.uint32)
        value = rng.integers(1, 1 << 20, (num_docs, k)).astype(np.uint32)
        words = kinds | (slot << 2) | (value << 12)
        counts = np.full((num_docs,), k, np.int32)
        base = np.full((num_docs,), 0, np.int32)
        batches.append(tuple(jax.device_put(a)
                             for a in (words, counts, base)))
    state0 = mk.init_state(num_docs, slots)

    def apply(s, b):
        return mpx.apply_tick_words_best(s, *b)

    s = apply(state0, batches[0])
    leaf = jax.tree_util.tree_leaves(s)[0]
    np.asarray(leaf[(0,) * leaf.ndim])

    for pace_ms in (0, 5, 10, 20):
        for depth in (16, 48):
            s = state0
            inflight = []
            enq_t = []
            completions = []
            lat = []
            next_t = time.perf_counter()
            for i in range(ticks + depth):
                if pace_ms:
                    now = time.perf_counter()
                    if now < next_t:
                        time.sleep(next_t - now)
                    next_t = max(next_t + pace_ms / 1e3,
                                 time.perf_counter())
                s = apply(s, batches[i % len(batches)])
                leaf = jax.tree_util.tree_leaves(s)[0]
                probe = leaf[(0,) * leaf.ndim]
                fn = getattr(probe, "copy_to_host_async", None)
                if fn is not None:
                    fn()
                enq_t.append(time.perf_counter())
                inflight.append(probe)
                if len(inflight) > depth:
                    np.asarray(inflight.pop(0))
                    t = time.perf_counter()
                    completions.append(t)
                    lat.append(t - enq_t[len(completions) - 1])
            while inflight:
                np.asarray(inflight.pop(0))
                t = time.perf_counter()
                completions.append(t)
                lat.append(t - enq_t[len(completions) - 1])
            d = np.diff(np.asarray(completions[:ticks])) * 1000
            latms = np.asarray(lat[:ticks]) * 1000
            print(f"pace={pace_ms:2d}ms depth={depth:2d} "
                  f"cad p50={np.percentile(d, 50):6.2f} "
                  f"p99={np.percentile(d, 99):7.2f} max={d.max():7.2f} "
                  f"stalls>25={int((d > 25).sum()):3d} | "
                  f"lat p50={np.percentile(latms, 50):7.1f} "
                  f"p99={np.percentile(latms, 99):7.1f}")
            sys.stdout.flush()


if __name__ == "__main__":
    main()
